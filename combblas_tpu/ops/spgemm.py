"""Local semiring SpGEMM — expansion / sort / compression (ESC).

The reference's local SpGEMM (``include/CombBLAS/mtSpGEMM.h:214-440``) runs a
two-pass symbolic+numeric hash/heap kernel with a per-column heap-vs-hash
choice (compression ratio < 2.0 → heap, :310-311) and OpenMP over columns.
Per-column dynamic hashing is hostile to TPU vectorization, so the TPU-native
kernel is the classic ESC formulation — every phase is a primitive XLA is
good at:

  1. EXPAND: one slot per scalar multiply (flop). For A entry (i,k,a) and
     B's row k, emit (i, j, a⊗b) for each (k,j,b) — flattened to a static
     ``flop_capacity`` via ``expand_ranges`` (no per-column loops).
  2. SORT: lexicographic (row, col) ``lax.sort`` — TPU's native sort.
  3. COMPRESS: segmented semiring fold + compaction (``SpTuples.compact``).

The symbolic pass of the reference (``estimateFLOP`` :1058,
``estimateNNZ_Hash`` :807) maps to ``flops`` below: exact flop counting is a
one-gather + segment-sum, and callers size ``flop_capacity`` from it outside
jit (capacities are trace-time constants — the XLA analog of the
reference's exact preallocation).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..semiring import Semiring
from .compressed import CSR
from .segment import expand_ranges
from .tuples import SpTuples

Array = jax.Array

#: Semiring add-monoid → XLA scatter combiner with a native lowering.
#: The dense-accumulator SpGEMM tier folds expansion slots straight into a
#: dense block with ``acc.at[idx].<combiner>`` — available exactly for the
#: monoids XLA can scatter-combine (the same add_kind fast-path contract
#: as ``ops/segment.py``); ``None`` means the tier must fall back to ESC.
_SCATTER_COMBINERS = {"sum": "add", "min": "min", "max": "max"}


def scatter_combine_for(sr: Semiring) -> str | None:
    """Name of the ``jnp.ndarray.at[...]`` combiner implementing
    ``sr.add`` (``"add"``/``"min"``/``"max"``), or None for generic
    monoids (which need the order-respecting segmented reduction)."""
    return _SCATTER_COMBINERS.get(sr.add_kind)


def flops(a: SpTuples, b_csr: CSR) -> Array:
    """Scalar-multiply count of a·b (≈ estimateFLOP, mtSpGEMM.h:1058).

    Accumulated in float32: true counts can exceed int32 at scale (the
    reference uses int64, which JAX disables by default), and a capacity
    estimate only needs ~7 significant digits — callers add multiplicative
    slack (see ``summa_capacities``).
    """
    assert a.ncols == b_csr.nrows
    lens_pad = jnp.concatenate([b_csr.row_lens(), jnp.zeros((1,), jnp.int32)])
    k = jnp.minimum(a.cols, b_csr.nrows)
    per_entry = jnp.where(a.valid_mask(), lens_pad[k], 0)
    return jnp.sum(per_entry.astype(jnp.float32))


#: Contiguous-lane width of the chunked expansion. The target chip's gather
#: unit is per-INDEX bound with payload lanes up to ~256 B nearly free
#: (benchmarks/results/PERF_NOTES_r2.md gatherw), while per-element random
#: gathers run only ~22-27 M/s at every table size
#: (scatter_probe_r3.txt) — so fetching B rows in W-wide contiguous
#: windows divides the expansion's gather count by ~W. Slot padding from
#: rounding each B-row walk up to W is 3-6% on R-MAT at W=32 (flops
#: concentrate in wide rows); ``flops_padded`` sizes it exactly.
CHUNK_W = 32


def flops_padded(a: SpTuples, b_csr: CSR, chunk_w: int = CHUNK_W) -> Array:
    """Slot count of the chunked expansion: per A-entry
    ``ceil(deg_B(col)/W) * W`` summed (>= ``flops``; the capacity
    ``expand`` actually needs).

    EXACT (unlike the float32-accumulated ``flops`` estimate): the CHUNK
    count sums in int32 (exact below 2^31 chunks ≈ 7e10 slots at W=32,
    far past HBM) and the float32 result is a multiple of W below
    2^24 * W slots, hence exactly representable — callers may pass
    ``int(flops_padded(...))`` with no slack.
    """
    assert a.ncols == b_csr.nrows
    lens_pad = jnp.concatenate([b_csr.row_lens(), jnp.zeros((1,), jnp.int32)])
    k = jnp.minimum(a.cols, b_csr.nrows)
    deg = jnp.where(a.valid_mask(), lens_pad[k], 0)
    nch = -(-deg // chunk_w)
    return jnp.sum(nch).astype(jnp.float32) * chunk_w


def expand(
    sr: Semiring,
    a: SpTuples,
    b_csr: CSR,
    flop_capacity: int,
    chunk_w: int = CHUNK_W,
) -> SpTuples:
    """EXPAND phase: uncombined product tuples (duplicates included).

    Output tile has shape (a.nrows, b.ncols) and capacity
    ``ceil(flop_capacity / chunk_w) * chunk_w``; work beyond it is silently
    truncated — callers must size via ``flops_padded`` (for exactness) or a
    proven bound.

    CHUNKED-ELL FORMULATION (round 3): one expansion slot per
    (A-entry, B-row W-chunk) instead of per flop. Each virtual entry
    issues ONE gather index whose payload is a contiguous W-window of B's
    indices/values (vmapped ``dynamic_slice`` → an XLA gather with
    ``slice_sizes=W`` — the same contiguous-lane pattern as the ELL SpMV,
    which the chip serves at ~130 M windows/s vs ~25 M/s for per-element
    gathers). The flop->owner map itself is the scatter+cummax
    ``expand_ranges`` over chunk counts (V ≈ flops/W entries instead of
    flops), so the whole phase does O(nnz + flops/W) random work plus
    streaming passes.
    """
    assert a.ncols == b_csr.nrows
    W = chunk_w
    v_capacity = -(-flop_capacity // W)
    # Pad one full window of sentinels: a row's last chunk may extend past
    # the valid data, and dynamic_slice would otherwise CLAMP the start
    # backward, silently gathering earlier rows' entries into live lanes.
    b_indices = jnp.concatenate(
        [b_csr.indices, jnp.full((W,), b_csr.ncols, jnp.int32)]
    )
    b_vals = jnp.concatenate(
        [b_csr.vals, jnp.zeros((W,), b_csr.vals.dtype)]
    )
    lens_pad = jnp.concatenate([b_csr.row_lens(), jnp.zeros((1,), jnp.int32)])
    starts_pad = jnp.concatenate([b_csr.indptr[:-1], jnp.zeros((1,), jnp.int32)])
    k = jnp.minimum(a.cols, b_csr.nrows)
    deg = jnp.where(a.valid_mask(), lens_pad[k], 0)
    nch = -(-deg // W)
    owner, chix, valid_v, _ = expand_ranges(nch, v_capacity)
    # per-virtual-entry (V-sized) gathers — V ≈ flops/W, all small tables
    a_rows_v = a.rows[owner]
    a_vals_v = a.vals[owner]
    k_v = jnp.minimum(a.cols[owner], b_csr.nrows)
    deg_v = lens_pad[k_v]
    b0 = jnp.where(valid_v, starts_pad[k_v] + chix * W, 0)
    # contiguous W-window gathers of B's indices and values
    # [V, W] computed-index gather; vmap(dynamic_slice) was measured 5-10x
    # SLOWER on the target chip despite its explicit contiguity (the
    # slice-gather lowering serializes; benchmarks/results/spgemm_r3a.txt)
    win = b0[:, None] + jnp.arange(W, dtype=jnp.int32)[None, :]
    bcols = b_indices[win]
    bvals = b_vals[win]
    lane = jnp.arange(W, dtype=jnp.int32)
    lane_ok = valid_v[:, None] & (chix[:, None] * W + lane[None, :] < deg_v[:, None])
    rows = jnp.where(lane_ok, a_rows_v[:, None], a.nrows).reshape(-1)
    cols = jnp.where(lane_ok, bcols, b_csr.ncols).reshape(-1)
    vals = sr.mul(a_vals_v[:, None], bvals).reshape(-1)
    return SpTuples(
        rows=rows,
        cols=cols,
        vals=vals,
        nnz=jnp.sum(lane_ok).astype(jnp.int32),
        nrows=a.nrows,
        ncols=b_csr.ncols,
    )


def local_spgemm(
    sr: Semiring,
    a: SpTuples,
    b_csr: CSR,
    *,
    flop_capacity: int,
    out_capacity: int,
) -> SpTuples:
    """C = A ⊗ B on one tile: expand → sort → compress.

    ≈ ``LocalHybridSpGEMM`` (mtSpGEMM.h:214) with the hash/heap accumulator
    replaced by sort+segmented-fold.
    """
    return expand(sr, a, b_csr, flop_capacity).compact(
        sr, capacity=out_capacity
    )


def densify(t: SpTuples, pad_rows: int, pad_cols: int, zero) -> Array:
    """Tile tuples → dense [pad_rows, pad_cols] (padding cells = ``zero``).

    The scatter uses sorted/unique index hints (tiles are compacted and
    row-major sortable), which XLA can turn into a vectorized store.
    """
    t = t.sort_rowmajor()
    # Invalid slots get DISTINCT out-of-bounds indices (base + slot id) so
    # the unique_indices contract holds even for padding; mode='drop'
    # discards them all. Sortedness survives: valid entries occupy an
    # ascending prefix below base, invalid tail slots get base + position.
    oob = pad_rows * pad_cols + jnp.arange(t.capacity, dtype=jnp.int32)
    flat = jnp.where(t.valid_mask(), t.rows * pad_cols + t.cols, oob)
    dense = jnp.full((pad_rows * pad_cols,), zero, t.vals.dtype)
    dense = dense.at[flat].set(
        t.vals, mode="drop", indices_are_sorted=True, unique_indices=True
    )
    return dense.reshape(pad_rows, pad_cols)


def sparsify_windowed(
    dense: Array, zero, nrows: int, ncols: int, capacity: int
) -> tuple[SpTuples, Array]:
    """Dense [R, C] → compacted row-major SpTuples, output-driven with
    CONTIGUOUS-WINDOW narrowing (round 4).

    The target chip prices every per-element RANDOM memory op at ~22 M/s
    but serves one-index CONTIGUOUS multi-lane windows at ~130 M/s
    (PERF_NOTES_r3 cost model), and streams elementwise passes at only
    ~1 G elem-op/s (probe_r4e) — so an extraction must (a) be output-
    driven (input-driven scatters pay per CELL, and the r2 binary-search
    sparsify paid ~14 random probes per slot), and (b) spend its few
    per-slot memory ops on windows, not point gathers.  Scheme:

      counts:  8-cell group counts + 128-cell chunk prefix tables (MXU /
               streaming passes over the dense input — no random ops)
      slots:   ``expand_ranges`` over the 2M chunk counts → each output
               slot learns its (chunk, rank-within-chunk) for one
               chunk-sized scatter + one output-sized cummax
      narrow:  TWO window gathers per slot — the chunk's 16-entry group-
               prefix window (locates the 8-cell group) and the group's 8
               values (locates the lane IN REGISTER: the winning lane is
               selected by comparing the group's running nonzero count to
               the residual rank — no take_along_axis anywhere)

    Exact, sorted row-major, ~2 window ops + ~40 lanes of vector work per
    output slot.  The Pallas butterfly-pack alternative
    (``ops/pallas_sparsify``) is bound by the same chip's ~1 G elem-op/s
    vector wall across its ~100+ routing passes and measures 4-10x slower
    at bench densities; it remains available for the high-density regime
    and as the documented routing-network experiment.
    """
    from .segment import expand_ranges

    R, C = dense.shape
    # fence: without it XLA rematerializes the PRODUCER of `dense` (e.g.
    # the whole MXU matmul) inside every lax.map step below — measured
    # 39.8 s vs 1.4 s at scale 14 (probe_r4 densespgemm vs pwindowed)
    dense = lax.optimization_barrier(dense)
    flat = dense.reshape(-1)
    ncell = R * C
    assert ncell % 128 == 0, (R, C)
    nch = ncell // 128
    mask = dense != zero
    if C != ncols:
        mask = mask & (jnp.arange(C, dtype=jnp.int32)[None, :] < ncols)
    if R != nrows:
        mask = mask & (jnp.arange(R, dtype=jnp.int32)[:, None] < nrows)
    # LAYOUT NOTE (the 16x-padding trap, probe_r4f): XLA:TPU tiles the two
    # minor dims to (8, 128), so any [N, 16] / [N, 8] intermediate pads
    # 8-16x — a [nch, 16, 8] view of the mask alone would materialize
    # 4.3 GB at scale 14.  Group counts therefore come from ONE MXU
    # matmul on the un-padded [nch, 128] layout, and the only [nch, 16]
    # arrays are two transients immediately flattened to 1-D tables.
    # On non-TPU backends the (8, 128) tiling does not exist and the
    # matmul is the EXPENSIVE op (XLA:CPU has no MXU; an emulated-bf16
    # dot dominated the windowed-tier extraction profile) — a plain
    # reshape-sum computes the same [nch, 16] counts as one streaming
    # pass there.
    if jax.default_backend() == "tpu":
        mrow = mask.reshape(nch, 128).astype(jnp.bfloat16)
        gsel = (
            lax.broadcasted_iota(jnp.int32, (128, 16), 0) // 8
            == lax.broadcasted_iota(jnp.int32, (128, 16), 1)
        ).astype(jnp.bfloat16)
        t8 = jnp.dot(mrow, gsel, preferred_element_type=jnp.float32)
        t8 = t8.astype(jnp.int32)  # [nch, 16] group counts (exact: <= 8)
    else:
        t8 = jnp.sum(
            mask.reshape(nch, 16, 8).astype(jnp.int32), axis=-1
        )  # [nch, 16] group counts (exact: <= 8)
    g8 = jnp.cumsum(t8, axis=1) - t8  # exclusive group prefix within chunk
    g8f = g8.reshape(-1)  # flat 1-D table: no lane padding
    tch = jnp.sum(t8, axis=1)  # [nch] chunk counts
    g8f, tch = lax.optimization_barrier((g8f, tch))  # same remat fence
    # output-slot arrays are cap-sized int32 (fine); the [slot, 16]/[slot,
    # 8] narrowing intermediates are NOT (they pad to [slot, 128]) — so
    # the narrowing runs as a lax.map over bounded slot chunks.
    cs = min(1 << 18, max(capacity, 1 << 10))
    cap_pad = -(-capacity // cs) * cs
    owner, t, valid, total = expand_ranges(tch, cap_pad)
    owner = jnp.minimum(owner, nch - 1)

    def narrow(args):
        owner, t, valid = args
        # level 1: 16-lane window of the chunk's group prefix
        w16 = g8f[owner[:, None] * 16
                  + jnp.arange(16, dtype=jnp.int32)[None, :]]
        le = w16 <= t[:, None]
        b = jnp.sum(le, axis=1).astype(jnp.int32) - 1  # group index
        r8 = t - jnp.max(jnp.where(le, w16, 0), axis=1)  # rank within group
        # level 2: the group's 8 cells (values + mask) in one window each
        gbase = (owner * 16 + b) * 8
        cell = gbase[:, None] + jnp.arange(8, dtype=jnp.int32)[None, :]
        w8 = flat[cell]
        m8 = w8 != zero
        if C != ncols:
            m8 = m8 & (cell % C < ncols)
        if R != nrows:
            m8 = m8 & (cell // C < nrows)
        m8i = m8.astype(jnp.int32)
        excl8 = jnp.cumsum(m8i, axis=1) - m8i
        sel = m8 & (excl8 == r8[:, None])  # exactly one lane per valid slot
        lane = jnp.sum(
            jnp.where(sel, jnp.arange(8, dtype=jnp.int32)[None, :], 0), axis=1
        )
        vals = jnp.sum(jnp.where(sel, w8, 0), axis=1)
        fi = gbase + lane
        rows = jnp.where(valid, fi // C, nrows).astype(jnp.int32)
        cols = jnp.where(valid, fi % C, ncols).astype(jnp.int32)
        return rows, cols, jnp.where(valid, vals, 0)

    ncb = cap_pad // cs
    rows, cols, vals = lax.map(
        narrow,
        (owner.reshape(ncb, cs), t.reshape(ncb, cs), valid.reshape(ncb, cs)),
    )
    rows = rows.reshape(-1)[:capacity]
    cols = cols.reshape(-1)[:capacity]
    vals = vals.reshape(-1)[:capacity]
    return (
        SpTuples(
            rows=rows, cols=cols, vals=vals,
            nnz=jnp.minimum(total, capacity).astype(jnp.int32),
            nrows=nrows, ncols=ncols,
        ),
        total,
    )


def sparsify(
    dense: Array, zero, nrows: int, ncols: int, capacity: int
) -> tuple[SpTuples, Array]:
    """Dense [R, C] block → (SpTuples with ``capacity`` slots, exact
    nonzero count).

    Row-structured extraction: per-row nonzero counts feed
    ``expand_ranges`` (whose binary search runs over the tiny [R+1]
    prefix array — cache-resident), and each slot finds its column with a
    manual binary search over its OWN row's prefix sums. A flat
    searchsorted over the full R*C cumsum measured 26 s for 33M queries
    on the target chip (0.78 us/query of HBM-random binary probes); the
    row-local formulation cuts the big-array probes ~2x and keeps the
    heavy first search in cache.
    """
    from .segment import expand_ranges

    R, C = dense.shape
    mask = dense != zero
    if C != ncols:
        mask = mask & (jnp.arange(C, dtype=jnp.int32)[None, :] < ncols)
    if R != nrows:
        mask = mask & (jnp.arange(R, dtype=jnp.int32)[:, None] < nrows)
    m32 = mask.astype(jnp.int32)
    rowcnt = jnp.sum(m32, axis=1)
    rowcum = jnp.cumsum(m32, axis=1).reshape(-1)  # flat [R*C]
    owner, offset, valid, total = expand_ranges(rowcnt, capacity)
    # smallest c with rowcum[owner, c] >= offset+1
    want = offset + 1
    lo = jnp.zeros((capacity,), jnp.int32)
    hi = jnp.full((capacity,), C - 1, jnp.int32)
    nsteps = max(int(np.ceil(np.log2(max(C, 2)))), 1)
    base = owner * C
    for _ in range(nsteps):
        mid = (lo + hi) >> 1
        v = rowcum[base + mid]
        lo = jnp.where(v < want, mid + 1, lo)
        hi = jnp.where(v < want, hi, mid)
    col = hi
    rows = jnp.where(valid, owner, nrows).astype(jnp.int32)
    cols = jnp.where(valid, col, ncols).astype(jnp.int32)
    vals = jnp.where(valid, dense.reshape(-1)[base + col], 0)
    return (
        SpTuples(
            rows=rows, cols=cols, vals=vals,
            nnz=jnp.minimum(total, capacity).astype(jnp.int32),
            nrows=nrows, ncols=ncols,
        ),
        total,
    )


# --- dense-accumulator block kernel (the windowed mid-scale tier) -----------


def accumulate_block_scatter(
    sr: Semiring,
    acc: Array,
    a: SpTuples,
    b_csr: CSR,
    *,
    row_lo: int,
    flop_capacity: int,
    chunk_w: int = 8,
) -> Array:
    """Fold one stage's expansion for output rows [row_lo, row_lo + Rb)
    into the dense accumulator ``acc`` [Rb, pad_cols] with a single
    semiring scatter — the sort-free ESC accumulate.

    The classic ESC pays a (row, col) sort over EVERY expansion slot to
    group duplicates; when the add monoid has a native scatter combiner
    (``scatter_combine_for``), grouping is instead one ``at[].{add,min,
    max}`` into a dense row block.  Expansion slots arrive row-major-ish
    (they follow A's entry order), so the scatter's write set walks the
    accumulator block-locally — on backends with cached scatter units
    (XLA:CPU) this runs ~7x the fully-random scatter rate, and the sort
    (the 87 s scale-16 ESC floor) disappears entirely.  On the target TPU
    (no scatter unit, PERF_NOTES_r4) the caller uses the ``dot`` backend
    instead; this function is the general-backend twin.

    ``a`` must already be row-masked to the block (rows outside the block
    carry the ``a.nrows`` sentinel): invalid slots produce flat indices
    >= Rb * pad_cols and are dropped by the scatter.  ``chunk_w`` is the
    expansion window width — the default 8 keeps slot padding ~1.1x for
    R-MAT-like degree tails (the scatter pays per SLOT, so padding is
    priced at full scatter cost here, unlike the gather-bound ESC
    expansion where W=32 amortizes indices).
    """
    comb = scatter_combine_for(sr)
    assert comb is not None, (
        f"semiring {sr.name} (add_kind={sr.add_kind}) has no scatter "
        "combiner; use the ESC path"
    )
    rb, pad_cols = acc.shape
    t = expand(sr, a, b_csr, flop_capacity, chunk_w=chunk_w)
    # invalid slots: rows == a.nrows >= row_lo + rb ⇒ flat >= rb*pad_cols
    flat = (t.rows - row_lo) * pad_cols + t.cols
    flat = jnp.where(t.valid_mask(), flat, rb * pad_cols)
    upd = getattr(acc.reshape(-1).at[flat], comb)(
        t.vals, mode="drop"
    )
    return upd.reshape(rb, pad_cols)


def mask_rows(t: SpTuples, row_lo: int, row_hi: int) -> SpTuples:
    """Entries with row outside [row_lo, row_hi) become padding (sentinel
    indices) — the static row-block restriction of the windowed tier.
    ``nnz`` is recomputed; capacity is untouched (static shapes)."""
    import dataclasses

    keep = t.valid_mask() & (t.rows >= row_lo) & (t.rows < row_hi)
    return dataclasses.replace(
        t,
        rows=jnp.where(keep, t.rows, t.nrows),
        cols=jnp.where(keep, t.cols, t.ncols),
        nnz=jnp.sum(keep).astype(jnp.int32),
    )


def densify_combine(
    sr: Semiring, t: SpTuples, pad_rows: int, pad_cols: int
) -> Array:
    """Tile tuples → dense [pad_rows, pad_cols], duplicate slots COMBINED
    with the semiring's add monoid (``at[].{add,min,max}``).

    The duplicate-safe twin of ``densify``: that one claims
    ``unique_indices`` (undefined result on repeated (row, col) slots —
    the mxu tier's documented precondition), this one folds repeats with
    the same combiner the scatter backend uses, so every densifying
    consumer of it absorbs duplicate-entry COO inputs exactly.  No sort
    is needed (unsorted scatters combine associatively), which also makes
    it the cheaper choice for per-stage/per-window panel builds.  Only
    defined for semirings with a native scatter combiner
    (``scatter_combine_for``); cells with no entries hold ``sr.zero``.
    """
    comb = scatter_combine_for(sr)
    assert comb is not None, (
        f"semiring {sr.name} (add_kind={sr.add_kind}) has no scatter "
        "combiner; use densify on pre-compacted tiles instead"
    )
    zero = sr.zero(t.vals.dtype)
    ok = t.valid_mask() & (t.rows < pad_rows) & (t.cols < pad_cols)
    flat = jnp.where(ok, t.rows * pad_cols + t.cols, pad_rows * pad_cols)
    dense = jnp.full((pad_rows * pad_cols,), zero, t.vals.dtype)
    dense = getattr(dense.at[flat], comb)(t.vals, mode="drop")
    return dense.reshape(pad_rows, pad_cols)


def support_window_counts(
    bits: Array,
    block_rows: int,
    block_cols: int,
    nrows: int,
    ncols: int,
) -> Array:
    """Exact per-(row-block, col-window) output nnz from a packed support
    bitmask (``spgemm_support_bits`` / ``pack_support_bits`` layout):
    [nblocks, ncolwin] int32 — the oracle seeding of the 2D windowed
    plan (out caps become exact counts instead of clamped-flops bounds).

    ``block_cols`` must be word-aligned (multiple of 32) so every window
    covers whole uint32 words; bits past ``ncols`` are never set by the
    packers, so no tail masking is needed.
    """
    assert block_cols % 32 == 0, block_cols
    m, nw = bits.shape
    assert m == nrows, (m, nrows)
    nblocks = -(-nrows // block_rows)
    ncw = -(-ncols // block_cols)
    wpc = lax.population_count(bits).astype(jnp.int32)  # [m, nw]
    hid = (jnp.arange(nw, dtype=jnp.int32) * 32) // block_cols
    onehot = (hid[:, None] == jnp.arange(ncw, dtype=jnp.int32)[None, :])
    per_rh = jnp.dot(
        wpc.astype(jnp.float32), onehot.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    ).astype(jnp.int32)  # [m, ncw] (exact: counts < 2^24)
    g = jnp.arange(m, dtype=jnp.int32) // block_rows
    return jax.ops.segment_sum(per_rh, g, num_segments=nblocks)


# --- structure-aware merges (round 13: the sort-free combine tiers) ---------
#
# Every distributed SpGEMM schedule ends in the same step: combine the
# partial-product pieces that land on a device (SUMMA stage chunks, 3D
# fiber pieces) into one compacted tile.  The classic path is
# concat + full ``lax.sort`` (``SpTuples.compact``) — O(nnz·log nnz)
# comparisons over the WHOLE concatenation, re-deriving order the
# pieces already have.  The reference's distributed hash-SpGEMM (the
# 4.88 s scale-22 bar, SURVEY §2.2) never pays that sort; these two
# tiers are its TPU-native analogs:
#
#   ``merge_sorted_runs``  pieces that are already (row, col)-sorted
#                          (windowed-tier extractions, pre-sorted fiber
#                          pieces) merge by rank-space union — each
#                          element finds its output slot with
#                          lexicographic binary searches against the
#                          OTHER runs (O(nnz·log L) search levels), no
#                          sort anywhere.  Bit-exact with concat+sort
#                          for every semiring: equal keys stay in run
#                          order, so the segmented fold sees the same
#                          operand order.
#   ``hash_merge``         high-collision reduces combine through a
#                          bounded open-addressing table (scatter-probe
#                          claim, semiring combine on hit) — O(nnz)
#                          expected work independent of run count, with
#                          a COUNTED overflow so callers fall back to
#                          the sorted merge (never wrong, only slower).


def _lex_searchsorted(rs: Array, cs: Array, rq: Array, cq: Array,
                      side: str = "left") -> Array:
    """Vectorized ``searchsorted`` over LEXICOGRAPHIC (row, col) keys:
    for each query (rq, cq), the count of entries in the sorted run
    (rs, cs) strictly less than it (``side="left"``) or
    less-or-equal (``side="right"``).

    A single fused int key overflows int32 for large tiles
    (row·ncols + col exceeds 2^31 well inside the windowed envelope),
    so the comparison stays two-key; the binary search runs
    ceil(log2(n+1)) vectorized steps of one gather each — the same
    in-register search pattern as ``sparsify``."""
    assert side in ("left", "right"), side
    n = rs.shape[0]
    lo = jnp.zeros(rq.shape, jnp.int32)
    hi = jnp.full(rq.shape, n, jnp.int32)
    nsteps = max(int(np.ceil(np.log2(n + 1))), 1)
    for _ in range(nsteps):
        mid = (lo + hi) >> 1
        rm = rs[jnp.minimum(mid, n - 1)]
        cm = cs[jnp.minimum(mid, n - 1)]
        if side == "left":
            before = (rm < rq) | ((rm == rq) & (cm < cq))
        else:
            before = (rm < rq) | ((rm == rq) & (cm <= cq))
        adv = (lo < hi) & before
        ret = (lo < hi) & ~before
        lo = jnp.where(adv, mid + 1, lo)
        hi = jnp.where(ret, mid, hi)
    return lo


def _merge_two_sorted(x: SpTuples, y: SpTuples) -> SpTuples:
    """Merge two (row, col)-sorted tiles (padding sentinels at the
    tail) into one sorted tile of capacity ``x.capacity + y.capacity``.

    Rank-space union: x[i]'s output slot is ``i + |{y < x[i]}|`` and
    y[j]'s is ``j + |{x <= y[j]}|`` — a permutation by construction
    (ties resolve x-before-y, preserving concat order, so a downstream
    segmented fold is BIT-EXACT with the concat+sort path even for
    order-sensitive float accumulation).  Sentinel slots (row == nrows)
    compare greater than every valid key and equal to each other, so
    they land — x's first, then y's — on the output tail: padding
    stays a suffix and ``valid_mask`` semantics survive."""
    assert (x.nrows, x.ncols) == (y.nrows, y.ncols), (x, y)
    mx, my = x.capacity, y.capacity
    px = jnp.arange(mx, dtype=jnp.int32) + _lex_searchsorted(
        y.rows, y.cols, x.rows, x.cols, side="left"
    )
    py = jnp.arange(my, dtype=jnp.int32) + _lex_searchsorted(
        x.rows, x.cols, y.rows, y.cols, side="right"
    )

    def weave(ax, ay):
        out = jnp.zeros((mx + my,), ax.dtype)
        out = out.at[px].set(ax, unique_indices=True)
        return out.at[py].set(ay, unique_indices=True)

    return SpTuples(
        rows=weave(x.rows, y.rows),
        cols=weave(x.cols, y.cols),
        vals=weave(x.vals, y.vals),
        nnz=x.nnz + y.nnz,
        nrows=x.nrows, ncols=x.ncols,
    )


def merge_sorted_runs(runs: list[SpTuples]) -> SpTuples:
    """k-way merge of (row, col)-sorted same-shape tiles into ONE
    sorted tile (duplicates preserved, adjacent) — the sort-free
    replacement for ``SpTuples.concat(runs).sort_rowmajor()``.

    Pairwise tree merge: ceil(log2(L)) levels of ``_merge_two_sorted``
    rank-space unions, O(total · log L) binary-search levels instead of
    the full sort's O(total · log total) comparison passes — and each
    level is gathers + two scatters, which the CPU/TPU backends serve
    far faster than ``lax.sort``'s data-movement passes.  Adjacent
    pairing keeps ties in ascending run order at every level, so the
    output's duplicate groups appear in EXACT concat order (the
    bit-exactness contract callers' ``compact(assume_sorted=True)``
    relies on).  Callers must guarantee each run is individually
    sorted; ``mesh3d._fiber_exchange(sort_pieces=True)`` is the
    pre-sort for producers that aren't."""
    assert runs, "merge_sorted_runs needs at least one run"
    while len(runs) > 1:
        nxt = [
            _merge_two_sorted(runs[i], runs[i + 1])
            for i in range(0, len(runs) - 1, 2)
        ]
        if len(runs) % 2:
            nxt.append(runs[-1])
        runs = nxt
    return runs[0]


def hash_table_capacity(out_capacity: int) -> int:
    """Static open-addressing table size for ``hash_merge``: the next
    pow2 at or above 4× the distinct-key bound keeps the load factor
    ≤ 0.25.  With double hashing the chance an element exhausts k
    probes is ≈ α^k, so α=0.25 with the default 16 rounds puts the
    expected overflow (→ sorted-merge rerun) below 1e-9 per element —
    the fallback stays a safety net, not a steady-state tax.  (2×/8
    rounds measured ~3e-4 per element: one rerun per few thousand
    entries, far too hot for the multi-million-entry reduces this
    tier targets.)"""
    return 1 << max(int(4 * max(out_capacity, 8)) - 1, 1).bit_length()


def hash_merge(
    sr: Semiring,
    t: SpTuples,
    *,
    out_capacity: int,
    table_capacity: int,
    n_probes: int = 16,
) -> tuple[SpTuples, Array, Array]:
    """Combine duplicate (row, col) keys of ``t`` through a bounded
    open-addressing table — the hash-accumulator merge tier
    (≈ the reference's distributed hash-SpGEMM combine, SURVEY §2.2,
    with the per-column dynamic table replaced by ONE fixed
    ``table_capacity`` buffer and data-parallel scatter probing).

    Per probe round (static unroll, double hashing over the pow2
    table): unplaced elements gather their slot's key; empty slots are
    CLAIMED by a scatter-min winner which installs its key; every
    element whose slot now holds ITS key folds its value in with the
    add monoid's native scatter combiner and retires.  Elements still
    unplaced after ``n_probes`` rounds are COUNTED, not dropped —
    callers watch the overflow and rerun through the sorted-merge
    tier (never wrong, only slower).

    Returns ``(out, overflow, distinct)``: ``out`` is the compacted
    (UNSORTED — table-order) tile truncated to ``out_capacity``;
    ``distinct`` is the exact distinct-nonzero-key count so callers
    detect out_capacity truncation the usual way.  Only defined for
    semirings with a native scatter combiner."""
    comb = scatter_combine_for(sr)
    assert comb is not None, (
        f"semiring {sr.name} (add_kind={sr.add_kind}) has no scatter "
        "combiner; use merge_sorted_runs / the sort path"
    )
    T = int(table_capacity)
    assert T >= 2 and T & (T - 1) == 0, f"table capacity {T} not pow2"
    cap = t.capacity
    valid = t.valid_mask()
    zero = sr.zero(t.vals.dtype)

    def _mix(x):
        # finalizer-style avalanche (splitmix32 constants): adjacent
        # (row, col) keys — the common case for sorted pieces — must
        # not probe adjacent slots in lockstep
        x = (x ^ (x >> 16)) * jnp.uint32(0x7FEB352D)
        x = (x ^ (x >> 15)) * jnp.uint32(0x846CA68B)
        return x ^ (x >> 16)

    k = (
        t.rows.astype(jnp.uint32) * jnp.uint32(0x9E3779B1)
        + t.cols.astype(jnp.uint32) * jnp.uint32(0x85EBCA77)
    )
    h0 = (_mix(k) & jnp.uint32(T - 1)).astype(jnp.int32)
    # odd step cycles the whole pow2 table (double hashing)
    step = (
        (_mix(k ^ jnp.uint32(0xC2B2AE35)) | jnp.uint32(1))
        & jnp.uint32(T - 1)
    ).astype(jnp.int32) | 1
    t_rows = jnp.full((T,), t.nrows, jnp.int32)
    t_cols = jnp.full((T,), t.ncols, jnp.int32)
    t_vals = jnp.full((T,), zero, t.vals.dtype)
    slot_ids = jnp.arange(cap, dtype=jnp.int32)
    placed = ~valid
    slot = h0
    for round_ in range(n_probes):
        if round_:
            slot = (slot + step) & (T - 1)
        active = ~placed
        empty = t_rows[slot] == t.nrows
        # claim: lowest proposing element index wins each empty slot
        prop = jnp.where(active & empty, slot, T)
        winner = jnp.full((T,), cap, jnp.int32).at[prop].min(
            slot_ids, mode="drop"
        )
        inst = active & empty & (winner[slot] == slot_ids)
        # distinct OOB sentinels for non-installers (densify's
        # unique_indices convention)
        inst_slot = jnp.where(inst, slot, T + slot_ids)
        t_rows = t_rows.at[inst_slot].set(
            t.rows, mode="drop", unique_indices=True
        )
        t_cols = t_cols.at[inst_slot].set(
            t.cols, mode="drop", unique_indices=True
        )
        # combine into any slot now holding MY key (the installer and
        # every duplicate retire together)
        match = active & (t_rows[slot] == t.rows) & (t_cols[slot] == t.cols)
        t_vals = getattr(
            t_vals.at[jnp.where(match, slot, T)], comb
        )(t.vals, mode="drop")
        placed = placed | match
    overflow = jnp.sum(~placed).astype(jnp.int32)
    table = SpTuples(
        rows=t_rows, cols=t_cols, vals=t_vals,
        nnz=jnp.sum(t_rows < t.nrows).astype(jnp.int32),
        nrows=t.nrows, ncols=t.ncols,
    )
    # compact + prune additive identities (compact's prune_zeros
    # semantics), then truncate to the caller's static output shape
    out = table._select((t_rows < t.nrows) & (t_vals != zero))
    distinct = out.nnz
    return out.with_capacity(out_capacity), overflow, distinct


# --- bit-packed output-support oracle ---------------------------------------


def coo_sort_dedup(rows: Array, cols: Array) -> tuple[Array, Array, Array]:
    """Stable two-key sort (rows major, cols minor) + adjacent-repeat
    mask for a COO edge list.  Every bit-packed kernel must group and
    mask duplicated input entries on device (a duplicate would double-ADD
    a bit, carrying into the NEXT bit — ADVICE r5).  Returns the
    reordered (rows, cols) and the per-slot ``dup`` mask (True on every
    repeat after the first of a group).  Shared by the edge-harvest TC
    kernels (models/tc.py) and ``pack_support_bits``."""
    order_c = jnp.argsort(cols, stable=True)
    r1, c1 = rows[order_c], cols[order_c]
    order_r = jnp.argsort(r1, stable=True)
    rows, cols = r1[order_r], c1[order_r]
    dup = jnp.concatenate([
        jnp.zeros((1,), bool),
        (rows[1:] == rows[:-1]) & (cols[1:] == cols[:-1]),
    ])
    return rows, cols, dup


def pack_support_bits(
    rows: Array,
    cols: Array,
    nrows: int,
    ncols: int,
    *,
    assume_unique: bool = False,
) -> Array:
    """COO support → packed [nrows, ceil(ncols/32)] uint32 bitmask.

    Bit (i, j) is set iff some entry (i, j) exists with i < nrows and
    j < ncols — sentinel/padded slots (row >= nrows) drop out via the
    scatter's ``mode='drop'``.  Packing is a scatter-ADD of
    ``2^(j mod 32)`` at (i, j div 32); duplicates would carry into the
    next bit, so the input is ``coo_sort_dedup``-masked first unless the
    caller guarantees uniqueness (e.g. compacted SpTuples).

    This is the storage format of the output-support oracle: 32x less
    memory and gather traffic than a bool matrix, and intersection
    queries are ``popcount(a & b)`` (see ``popcount_pair_counts``).
    """
    nw = -(-ncols // 32)
    if not assume_unique:
        rows, cols, dup = coo_sort_dedup(rows, cols)
        rows = jnp.where(dup, nrows, rows)
    oob = (rows >= nrows) | (cols >= ncols)
    r = jnp.where(oob, nrows, rows)
    bits = jnp.zeros((nrows, nw), jnp.uint32)
    return bits.at[r, cols >> 5].add(
        jnp.uint32(1) << (cols.astype(jnp.uint32) & 31), mode="drop"
    )


def popcount_pair_counts(
    bits_i: Array,
    bits_j: Array,
    ii: Array,
    jj: Array,
    weights: Array,
    *,
    chunk: int = 8192,
) -> Array:
    """Σ_pairs weights · popcount(bits_i[ii] ∩ bits_j[jj]) as an int32
    (hi, lo) 15-bit split (totals can exceed 2^31; int64 is unavailable
    without x64 mode — same rationale as models/tc.py).

    The masked-SpGEMM numeric pass for 0/1-valued plus_times products:
    each (i, j) pair's count is the exact C[i,j] = Σ_k A[i,k]·B[k,j]
    restricted to the pair list (the output-support mask).  A lax.scan
    walks static ``chunk``-sized pair blocks; per step two row gathers of
    the packed tables + a streaming popcount — the bit-packed
    edge-harvest inner loop (models/tc.py) generalized to two distinct
    bit tables, which is what the DISTRIBUTED tier needs (row-block and
    col-block masks live on different devices).

    ``ii``/``jj``/``weights`` must be padded to a multiple of ``chunk``
    with weight-0 slots (indices clamped in-range by the caller).
    """
    npairs = ii.shape[0]
    assert npairs % chunk == 0, (npairs, chunk)

    def body(carry, eidx):
        hi, lo = carry
        gi = bits_i[ii[eidx]]  # [chunk, nw] u32
        gj = bits_j[jj[eidx]]
        pc = lax.population_count(gi & gj)
        cnt = jnp.sum(pc.astype(jnp.int32), axis=1) * weights[eidx]
        # renormalize the split each step: an unbounded lo accumulation
        # would itself wrap past 2^31 (models/tc.py rationale)
        lo = lo + jnp.sum(cnt & 0x7FFF)
        hi = hi + jnp.sum(cnt >> 15) + (lo >> 15)
        lo = lo & 0x7FFF
        return (hi, lo), None

    idx = jnp.arange(npairs, dtype=jnp.int32).reshape(-1, chunk)
    (hi, lo), _ = lax.scan(body, (jnp.int32(0), jnp.int32(0)), idx)
    return jnp.stack([hi, lo])


def combine_hilo(hilo) -> int:
    """Exact host-side total from an int32 (hi, lo) 15-bit split."""
    hilo = np.asarray(hilo, np.int64)
    return int((hilo[0] << 15) + hilo[1])


def spgemm_support_bits(
    a: SpTuples,
    b: SpTuples,
    *,
    row_block: int = 4096,
) -> tuple[Array, Array]:
    """Output-support oracle: the boolean pattern of a·b as a packed
    [a.nrows, ceil(b.ncols/32)] uint32 bitmask, plus exact per-row
    nonzero counts.

    The pattern is computed as a row-blocked COUNTS product on the
    matrix unit — bool(A) @ bool(B) in bf16 (0/1 inputs are exact; f32-
    accumulated counts are exact below 2^24 ≈ any k <= 16M) — then
    thresholded and bit-packed immediately, so only one [row_block,
    ncols] dense block is ever live: the "cheap MXU work first" half of
    the masked-SpGEMM design.  Callers run the numeric pass only over
    the support (``popcount_pair_counts`` for 0/1 plus_times;
    masked gather-dot for general values).

    Only sensible where the dense operands fit (the MXU-tier envelope);
    the windowed tier uses host symbolic sizing instead at larger
    scales.
    """
    assert a.ncols == b.nrows
    m, k, n = a.nrows, a.ncols, b.ncols
    kpad = -(-k // 128) * 128
    npad = -(-n // 128) * 128
    nw = -(-n // 32)

    def support_dense(t: SpTuples, R: int, C: int) -> Array:
        # 0/1 support via scatter-ADD + clamp: duplicate-entry safe
        # (densify's unique_indices contract would be violated by
        # repeated slots) and sort-free.
        flat = jnp.where(t.valid_mask(), t.rows * C + t.cols, R * C)
        d = jnp.zeros((R * C,), jnp.float32).at[flat].add(
            1.0, mode="drop"
        )
        return jnp.minimum(d, 1.0).reshape(R, C)

    da = support_dense(a, -(-m // row_block) * row_block, kpad)
    db = support_dense(b, kpad, npad)
    da = da.astype(jnp.bfloat16)
    db = db.astype(jnp.bfloat16)
    lanes = jnp.arange(32, dtype=jnp.uint32)
    out_bits = []
    out_cnt = []
    nblocks = -(-m // row_block)
    for blk in range(nblocks):
        lo = blk * row_block
        cnt = jnp.dot(
            da[lo:lo + row_block], db, preferred_element_type=jnp.float32
        )
        live = cnt[:, :n] > 0
        out_cnt.append(jnp.sum(live, axis=1).astype(jnp.int32))
        lv = jnp.pad(live, ((0, 0), (0, nw * 32 - n)))
        packed = jnp.sum(
            lv.reshape(row_block, nw, 32).astype(jnp.uint32)
            << lanes[None, None, :],
            axis=-1, dtype=jnp.uint32,
        )
        out_bits.append(packed)
    bits = jnp.concatenate(out_bits)[:m]
    row_nnz = jnp.concatenate(out_cnt)[:m]
    return bits, row_nnz


def dense_support_nnz(dense: Array, zero, nrows: int, ncols: int) -> Array:
    """Exact nonzero count of a (possibly padded) dense block — the
    output-support size, used to size sparse extraction capacities
    exactly instead of guess-and-retry (models/mcl.py dense path)."""
    R, C = dense.shape
    mask = dense != zero
    if C != ncols:
        mask = mask & (jnp.arange(C, dtype=jnp.int32)[None, :] < ncols)
    if R != nrows:
        mask = mask & (jnp.arange(R, dtype=jnp.int32)[:, None] < nrows)
    return jnp.sum(mask).astype(jnp.int32)
