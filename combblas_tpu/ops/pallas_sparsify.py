"""Pallas dense→sparse compaction — the round-4 "break the 22 M/s wall" kernel.

THE PROBLEM.  Every per-element random memory op on the target chip runs at
~21-27 M/s (gather, scatter, segment-sum — measured, PERF_NOTES_r3.md), so
any XLA formulation of "extract the nonzeros of a dense matrix" pays ≥1-2
output-sized random passes: ~1-2 s for a 20M-nonzero extraction.  That tax
is what capped the round-2 dense-block SpGEMM at 2.9 MFLOP/s (36 s, almost
all in ``sparsify``'s binary searches) and what VERDICT r3 item 1 demands a
Pallas answer to.  The reference gets the same job done with cache-resident
hash accumulation (``mtSpGEMM.h:214-440``); the TPU has no scatter unit at
all — Mosaic rejects even scalar stores to VMEM ("Cannot store scalars to
VMEM", benchmarks/results/probe_r4b.txt) — so the fix cannot be "scatter,
but in VMEM".  Contiguity has to be MANUFACTURED with vector primitives.

THE KERNEL.  Compaction is a MONOTONE ROUTING problem, and monotone routes
run conflict-free through a butterfly: element j with rank r_j (exclusive
prefix-count of preceding nonzeros) must move LEFT by d_j = j - r_j, and
since d_j is non-decreasing along j, applying the binary decomposition of
d_j one bit per stage (shift-by-2^s where bit s of d is set) never lands
two elements on one slot.  (Proof: after stage s every survivor sits at
r_j + 2^(s+1) * floor(d_j / 2^(s+1)); for j1 < j2 both terms are ordered —
r strictly increases, floor is non-decreasing — so positions stay
distinct.)  Each stage is a few ``pltpu.roll``s and selects per carried
array — pure VPU work on VMEM-resident vregs, NO random memory ops.

The matrix streams through the kernel as the FLAT row-major [M*N/128, 128]
view (a free XLA reshape — row-major bitcast), in panels of
``_PANEL_ROWS`` x 128 elements:

  rank:   lane-axis log-shift prefix sums + a sublane-offset cascade
  route:  log2(panel) butterfly stages of roll+select
  write:  ONE sequential 8-row-aligned DMA per panel at a running offset
          (SMEM carry), sized from a static row-bucket ladder; bucket
          slack is sentinel-filled, inter-panel gaps are < 1024 elements
          and read as padding (SpTuples tolerates non-prefix padding)

Panels walk the flat stream in order, so the packed output is EXACTLY the
row-major nonzero stream — a sorted, (almost-)compacted SpTuples with no
further sort.

Throughput model: ~21 stages x ~10 vector ops per panel ≈ 250 VMEM passes
at VPU rates ≈ tens of ms for a 1 GB matrix — versus ~2 s for the XLA
scatter path and ~26-36 s for the round-2 searchsorted path.

Reference counterpart: the dense→sparse leg of ``SpTuples`` construction /
``Dcsc`` build; the performance role matches the in-cache accumulator of
``mtSpGEMM.h`` (what lets SpGEMM emit sparse output at memory speed).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

Array = jax.Array

#: Flat-view panel height (x128 lanes = 1M elements per panel).
_PANEL_ROWS = 8192

#: Row-count ladder for the per-panel output DMA: the smallest bucket
#: >= ceil(count/128) rows is written (bucket slack is sentinel-filled).
#: Multiples of 8 — Mosaic requires dim-0 slices aligned to the (8, 128)
#: tile, and the running output offset stays 8-aligned the same way.
_ROW_BUCKETS = (8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096)


def _leftshift(x: Array, t: int) -> Array:
    """Flat left-shift by t over the row-major [R, 128] layout:
    out[r, c] = x_flat[r*128 + c + t] (cyclic junk at the very end)."""
    R = x.shape[0]
    sub, lane = divmod(t, 128)
    if lane == 0:
        return pltpu.roll(x, (R - sub) % max(R, 1), 0)
    y = pltpu.roll(x, 128 - lane, 1)  # y[r, c] = x[r, (c + lane) % 128]
    ynext = pltpu.roll(y, (R - sub - 1) % R, 0)
    ycur = pltpu.roll(y, (R - sub) % R, 0) if sub else y
    cc = lax.broadcasted_iota(jnp.int32, x.shape, 1)
    return jnp.where(cc < 128 - lane, ycur, ynext)


def _prefix_ranks(mask_i32: Array) -> Array:
    """Exclusive prefix-count of ``mask_i32 [R, 128]`` in row-major flat
    order: log2(128) lane shift-adds + log2(R) sublane shift-adds."""
    R = mask_i32.shape[0]
    acc = mask_i32
    t = 1
    while t < 128:
        sh = pltpu.roll(acc, t, 1)  # sh[r, c] = acc[r, c - t]
        cc = lax.broadcasted_iota(jnp.int32, acc.shape, 1)
        acc = acc + jnp.where(cc >= t, sh, 0)
        t *= 2
    row_tot = acc[:, 127:]  # [R, 1] inclusive row totals
    rowoff = row_tot
    t = 1
    while t < R:
        sh = pltpu.roll(rowoff, t, 0)
        rr = lax.broadcasted_iota(jnp.int32, rowoff.shape, 0)
        rowoff = rowoff + jnp.where(rr >= t, sh, 0)
        t *= 2
    rowoff = rowoff - row_tot  # exclusive row offsets
    return acc - mask_i32 + rowoff  # exclusive flat rank


def _pack_kernel(
    x_ref, idx_out_ref, val_out_ref, counts_ref, wrote_ref, off_sm,
    scratch_i, scratch_v, dma_sem, *, zero: float, pr: int, cap_rows: int,
):
    p = pl.program_id(0)

    @pl.when(p == 0)
    def _():
        off_sm[0] = 0

    x = x_ref[...]  # [pr, 128] flat panel
    mask = (x != zero).astype(jnp.int32)
    rank = _prefix_ranks(mask)
    total = jnp.sum(mask)
    rr = lax.broadcasted_iota(jnp.int32, x.shape, 0)
    cc = lax.broadcasted_iota(jnp.int32, x.shape, 1)
    flat = rr * 128 + cc
    # displacement; invalid slots carry d = -1 (doubles as routed validity)
    d = jnp.where(mask == 1, flat - rank, -1)
    vals = x
    idx = flat + p * (pr * 128)  # global flat index
    s = 1
    while s < pr * 128:
        d_in = _leftshift(d, s)
        take_in = (d_in >= 0) & ((d_in & s) != 0)
        keep = (d >= 0) & ((d & s) == 0)
        vals = jnp.where(take_in, _leftshift(vals, s), vals)
        idx = jnp.where(take_in, _leftshift(idx, s), idx)
        d = jnp.where(take_in, d_in - s, jnp.where(keep, d, -1))
        s *= 2
    # packed prefix + sentinel tail (bucket slack reads as padding)
    live = flat < total
    scratch_i[...] = jnp.where(live, idx, -1)
    scratch_v[...] = jnp.where(live, vals, jnp.asarray(zero, x.dtype))
    off = off_sm[0]
    rows_used8 = lax.div(total + (8 * 128 - 1), 8 * 128) * 8  # 8-aligned

    # smallest ladder bucket >= rows_used8, computed arithmetically so the
    # "did this panel get written" flag is exact (overflow never exposes
    # unwritten output rows as live)
    ladder = [b for b in _ROW_BUCKETS if b < pr] + [pr]
    chosen = jnp.int32(ladder[-1])
    for b in reversed(ladder):
        chosen = jnp.where(rows_used8 <= b, jnp.int32(b), chosen)
    # fire on the ADVANCE amount (rows_used8), not the bucket size: off
    # only ever grows by rows_used8, so `off + rows_used8 <= cap_rows` is
    # the exact "fits" test, and the bucket DMA's overhang past cap_rows
    # (chosen - rows_used8 < pr rows) lands in the pad_rows slack
    # allocated for exactly this (ADVICE r4: comparing the bucket-rounded
    # `chosen` dropped panels whole even when total <= capacity)
    fired = (total > 0) & (off + rows_used8 <= cap_rows)
    for b in ladder:

        @pl.when(fired & (chosen == b))
        def _(b=b):
            aligned_off = pl.multiple_of(off, 8)
            cp1 = pltpu.make_async_copy(
                scratch_i.at[pl.ds(0, b), :],
                idx_out_ref.at[pl.ds(aligned_off, b), :],
                dma_sem.at[0],
            )
            cp2 = pltpu.make_async_copy(
                scratch_v.at[pl.ds(0, b), :],
                val_out_ref.at[pl.ds(aligned_off, b), :],
                dma_sem.at[1],
            )
            cp1.start()
            cp2.start()
            cp1.wait()
            cp2.wait()

    counts_ref[p] = total
    wrote_ref[p] = jnp.where(fired, rows_used8, 0)
    off_sm[0] = off + jnp.where(fired, rows_used8, 0)


def flat_to_tuples_arrays(
    xf: Array,
    *,
    zero: float = 0.0,
    capacity: int,
    panel_rows: int = _PANEL_ROWS,
    interpret: bool = False,
) -> tuple[Array, Array, Array, Array]:
    """Compact the nonzeros (!= ``zero``) of the flat row-major view
    ``xf [R, 128]``.

    Returns (flat_idx int32 [cap], vals [cap], total int32, end_row int32):
    ``flat_idx`` holds global flat indices, ``-1`` on padding slots; valid
    slots are exactly ``(flat_idx >= 0) & (slot < end_row*128)``.
    ``total`` is the exact nonzero count even when it exceeds ``capacity``
    (the overflow-detection contract; overflowing panels are dropped
    whole).  R must divide by ``panel_rows`` (a multiple of 8).
    """
    import math

    R, L = xf.shape
    assert L == 128, xf.shape
    pr = math.gcd(R, min(panel_rows, R))  # largest pow2-ish divisor <= cap
    assert R % pr == 0 and pr % 8 == 0, (R, pr)
    npanels = R // pr
    # 8 extra rows per panel so rounding slack can never evict real
    # entries: total <= capacity implies every panel is written
    cap_rows = -(-capacity // 128)
    cap_rows = -(-cap_rows // 8) * 8 + 8 * npanels
    pad_rows = cap_rows + pr  # one full bucket may overhang past cap_rows
    kernel = functools.partial(
        _pack_kernel, zero=zero, pr=pr, cap_rows=cap_rows
    )
    idx_out, val_out, counts, wrote = pl.pallas_call(
        kernel,
        grid=(npanels,),
        in_specs=[
            pl.BlockSpec((pr, 128), lambda p: (p, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((pad_rows, 128), jnp.int32),
            jax.ShapeDtypeStruct((pad_rows, 128), xf.dtype),
            jax.ShapeDtypeStruct((npanels,), jnp.int32),
            jax.ShapeDtypeStruct((npanels,), jnp.int32),
        ),
        scratch_shapes=[
            pltpu.SMEM((1,), jnp.int32),
            pltpu.VMEM((pr, 128), jnp.int32),
            pltpu.VMEM((pr, 128), xf.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024,
        ),
        interpret=interpret,
    )(xf)
    total = jnp.sum(counts)
    end_row = jnp.sum(wrote)
    flat_cap = cap_rows * 128
    return (
        idx_out.reshape(-1)[:flat_cap],
        val_out.reshape(-1)[:flat_cap],
        total,
        end_row,
    )


def dense_to_tuples_arrays(
    x: Array,
    *,
    zero: float = 0.0,
    capacity: int,
    panel_rows: int = _PANEL_ROWS,
    interpret: bool = False,
) -> tuple[Array, Array, Array, Array]:
    """2-D entry: reshape ``x [M, N]`` to the flat [M*N/128, 128] view (a
    free row-major bitcast in XLA) and pack. See ``flat_to_tuples_arrays``.
    """
    M, N = x.shape
    assert (M * N) % 128 == 0, (M, N)
    return flat_to_tuples_arrays(
        x.reshape(-1, 128), zero=zero, capacity=capacity,
        panel_rows=panel_rows, interpret=interpret,
    )


def dense_to_sptuples(
    x: Array,
    nrows: int,
    ncols: int,
    *,
    zero: float = 0.0,
    capacity: int,
    panel_rows: int = _PANEL_ROWS,
    interpret: bool = False,
):
    """Dense [M>=nrows, N>=ncols] (padded) → row-major-sorted SpTuples +
    exact pre-truncation count.

    The Pallas replacement for ``ops.spgemm.sparsify`` (whose per-slot
    binary searches cost ~0.8 us each on the target chip).  Entries in
    padding rows/cols (>= nrows/ncols) must already equal ``zero``.  The
    result's padding is NOT a suffix (8-row-aligned inter-panel gaps hold
    sentinels) — fine for every masked op; run ``_select`` to canonicalize
    if a prefix layout is required.
    """
    from .tuples import SpTuples

    M, N = x.shape
    fi, fv, total, end_row = dense_to_tuples_arrays(
        x, zero=zero, capacity=capacity, panel_rows=panel_rows,
        interpret=interpret,
    )
    cap = fi.shape[0]
    live = (fi >= 0) & (jnp.arange(cap, dtype=jnp.int32) < end_row * 128)
    r = fi // N
    rows = jnp.where(live, r, nrows)
    cols = jnp.where(live, fi - r * N, ncols)
    vals = jnp.where(live, fv, 0)
    nnz = jnp.sum(live.astype(jnp.int32))
    return (
        SpTuples(
            rows=rows.astype(jnp.int32),
            cols=cols.astype(jnp.int32),
            vals=vals,
            nnz=nnz,
            nrows=nrows,
            ncols=ncols,
        ),
        total,
    )
