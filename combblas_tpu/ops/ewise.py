"""Elementwise set operations on sparse tiles via sort-merge.

The reference implements ``EWiseMult`` / ``EWiseApply`` with synchronized
column-pointer walks over two DCSC structures
(``include/CombBLAS/ParFriends.h:2157-2807``, ``Friends.h``).  The TPU-native
equivalent: concatenate both tiles' keys, lexicographic ``lax.sort``, and
detect matches by adjacency — O((nnzA+nnzB) log) fully vectorized work with
no data-dependent control flow, which XLA maps onto the TPU's native sort.

Avoids composite int64 keys on purpose: tile dims can make row*ncols+col
overflow int32, and int64 is off by default in JAX — multi-key sort + tag
ordering gives exact lexicographic semantics in pure int32.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .tuples import SpTuples

Array = jax.Array


def intersect_lookup(a: SpTuples, b: SpTuples, b_zero) -> tuple[Array, Array]:
    """For every slot of ``a``: is (row, col) present in ``b``, and b's value.

    Returns (hit[capA] bool, bval[capA]); misses get ``b_zero``.  ``b`` must
    be duplicate-free among valid entries (compacted).  Hits reported on
    padding slots of ``a`` are meaningless — callers must mask with
    ``a.valid_mask()``.
    """
    capa, capb = a.capacity, b.capacity
    rows = jnp.concatenate([b.rows, a.rows])
    cols = jnp.concatenate([b.cols, a.cols])
    # tag sorts b-entries immediately before a-entries with the same key
    tag = jnp.concatenate(
        [jnp.zeros((capb,), jnp.int32), jnp.ones((capa,), jnp.int32)]
    )
    bval = jnp.concatenate([b.vals, jnp.zeros((capa,), b.vals.dtype)])
    apos = jnp.concatenate(
        [jnp.full((capb,), capa, jnp.int32), jnp.arange(capa, dtype=jnp.int32)]
    )
    r, c, t, bv, ap = lax.sort((rows, cols, tag, bval, apos), num_keys=3)
    matched = (
        (r[1:] == r[:-1]) & (c[1:] == c[:-1]) & (t[1:] == 1) & (t[:-1] == 0)
    )
    hit_sorted = jnp.concatenate([jnp.zeros((1,), bool), matched])
    bv_prev = jnp.concatenate([bv[:1], bv[:-1]])
    scatter_idx = jnp.where(t == 1, ap, capa)
    hit = (
        jnp.zeros((capa,), bool).at[scatter_idx].set(hit_sorted, mode="drop")
    )
    bvals = (
        jnp.full((capa,), b_zero, dtype=b.vals.dtype)
        .at[scatter_idx]
        .set(jnp.where(hit_sorted, bv_prev, b_zero), mode="drop")
    )
    return hit, bvals


def ewise_apply(
    a: SpTuples,
    b: SpTuples,
    fn,
    *,
    allow_a_nulls: bool,
    allow_b_nulls: bool,
    a_null,
    b_null,
) -> SpTuples:
    """Generalized elementwise apply with null handling.

    Reference: ``EWiseApply`` (ParFriends.h:2157-2807): the output pattern is
    the intersection, optionally extended to entries present only in b
    (``allow_a_nulls`` — a's missing value is ``a_null``) and/or only in a
    (``allow_b_nulls``). ``fn(a_val, b_val)`` computes kept values. Both
    tiles must be compacted/duplicate-free. Output capacity is
    ``a.capacity + b.capacity`` (union bound).
    """
    # intersect_lookup fills misses with b_null already.
    hit_ab, bvals = intersect_lookup(
        a, b, b_zero=jnp.asarray(b_null, b.vals.dtype)
    )
    # a-side entries: intersection always; a-only iff allow_b_nulls.
    keep_a = a.valid_mask() & (hit_ab | allow_b_nulls)
    vals_a = jnp.where(keep_a, fn(a.vals, bvals), a.vals)
    a_side = SpTuples(
        rows=a.rows, cols=a.cols, vals=vals_a.astype(a.vals.dtype),
        nnz=a.nnz, nrows=a.nrows, ncols=a.ncols,
    )._select(keep_a)
    if not allow_a_nulls:
        return a_side  # pattern ⊆ a's entries: keep a's capacity
    # b-only entries.
    hit_ba, _ = intersect_lookup(b, a, b_zero=jnp.zeros((), a.vals.dtype))
    keep_b = b.valid_mask() & ~hit_ba
    vals_b = fn(jnp.asarray(a_null, a.vals.dtype), b.vals)
    b_side = SpTuples(
        rows=b.rows, cols=b.cols, vals=vals_b.astype(a.vals.dtype),
        nnz=b.nnz, nrows=b.nrows, ncols=b.ncols,
    )._select(keep_b)
    return SpTuples.concat([a_side, b_side])


def ewise_mult(a: SpTuples, b: SpTuples, negate: bool, combine=None) -> SpTuples:
    """A .* structure(B) (negate=False) or A .* ¬structure(B) (negate=True).

    ``combine(a_val, b_val)`` transforms kept values when intersecting
    (default keeps a's value — the reference's exclude=false semantics).
    Reference: ``EWiseMult`` (ParFriends.h:2157-2244).
    """
    hit, bvals = intersect_lookup(a, b, b_zero=jnp.zeros((), b.vals.dtype))
    keep = a.valid_mask() & (hit != negate)
    out = a
    if combine is not None and not negate:
        out = SpTuples(
            rows=a.rows, cols=a.cols,
            vals=jnp.where(keep, combine(a.vals, bvals), a.vals),
            nnz=a.nnz, nrows=a.nrows, ncols=a.ncols,
        )
    return out._select(keep)
