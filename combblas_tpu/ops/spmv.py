"""Local (single-tile) semiring mat-vec kernels.

TPU-native counterparts of the reference's sequential kernel layer:

* ``spmv``          ≈ ``dcsc_gespmv`` / ``dcsc_gespmv_threaded``
                      (``include/CombBLAS/Friends.h:64-180``) — dense x.
* ``spmspv``        ≈ ``SpImpl::SpMXSpV`` heap/bucket kernels
                      (``include/CombBLAS/SpImpl.h:47-200``, ``SpImpl.cpp``)
                      — sparse x, sparse y out.
* ``spmv_masked``   ≈ the Graph500 fused path (``BFSFriends.h:59-182``) where
                      already-visited rows are excluded before the reduction.

Design note: the reference parallelizes these with OpenMP row-splits; here
each kernel is a flat gather → elementwise ``mul`` → segment ``add`` chain
that XLA fuses and vectorizes over the 8×128 VPU lanes. Padding slots carry
out-of-range indices and are dropped by the scatter, so no masks are needed
on the hot path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..semiring import Semiring
from .compressed import CSC
from .segment import expand_ranges, segment_reduce
from .tuples import SpTuples

Array = jax.Array


def spmv(sr: Semiring, a: SpTuples, x: Array) -> Array:
    """Dense-vector semiring SpMV: ``y[i] = ⊕_j a[i,j] ⊗ x[j]``.

    ``x`` must have shape [ncols]; returns [nrows]. Rows with no valid
    entries get ``sr.zero``.
    """
    assert x.shape == (a.ncols,), (x.shape, a.ncols)
    zero = sr.zero(x.dtype)
    x_pad = jnp.concatenate([x, zero[None]])
    prods = sr.mul(a.vals, x_pad[a.cols])
    return segment_reduce(sr, prods, a.rows, a.nrows)


def spmv_masked(sr: Semiring, a: SpTuples, x: Array, row_active: Array) -> Array:
    """SpMV that suppresses output rows where ``row_active`` is False.

    The suppressed rows get ``sr.zero``; this is the local analog of the
    reference's fused BFS kernel which skips already-discovered vertices
    (``BFSFriends.h:59-182`` BitMap dedup).
    """
    y = spmv(sr, a, x)
    return jnp.where(row_active, y, sr.zero(y.dtype))


def _expand_products(
    sr: Semiring, a_csc: CSC, x_ind: Array, x_val: Array, exp_capacity: int
) -> tuple[Array, Array]:
    """Walk active columns, flattening (entry, active col) pairs into
    ``exp_capacity`` static slots → (row ids, semiring products).

    Precondition: distinct valid x_ind and total active-column length
    <= exp_capacity (overflowing pairs are silently dropped — callers bound
    the frontier edge count before choosing this kernel).
    """
    x_ind = jnp.where(x_ind < a_csc.ncols, x_ind, a_csc.ncols)
    lens_pad = jnp.concatenate([a_csc.col_lens(), jnp.zeros((1,), jnp.int32)])
    starts_pad = jnp.concatenate(
        [a_csc.indptr[:-1], jnp.zeros((1,), jnp.int32)]
    )
    xlens = lens_pad[jnp.minimum(x_ind, a_csc.ncols)]
    owner, offset, valid, _total = expand_ranges(xlens, exp_capacity)
    src_col_start = starts_pad[jnp.minimum(x_ind[owner], a_csc.ncols)]
    slot = src_col_start + offset
    row = jnp.where(valid, a_csc.indices[slot], a_csc.nrows)
    prod = sr.mul(a_csc.vals[slot], x_val[owner])
    return row, prod


def spmspv_dense_out(
    sr: Semiring,
    a_csc: CSC,
    x_ind: Array,
    x_val: Array,
    *,
    exp_capacity: int,
) -> Array:
    """Sparse-x, DENSE-y semiring SpMSpV: ``y[i] = ⊕ a[i,j] ⊗ x[j]`` over
    active columns j; untouched rows get ``sr.zero``.

    The top-down BFS kernel: work scales with ``exp_capacity`` (the frontier
    edge budget), not the tile nnz — the static-shape counterpart of the
    reference's "touch only active columns" SpMSpV advantage
    (``SpImpl.cpp:390-600``). The distributed driver checks the global
    frontier edge count against the budget before selecting this kernel.
    """
    row, prod = _expand_products(sr, a_csc, x_ind, x_val, exp_capacity)
    return segment_reduce(sr, prod, row, a_csc.nrows)


def spmspv(
    sr: Semiring,
    a_csc: CSC,
    x_ind: Array,
    x_val: Array,
    x_nnz: Array,
    *,
    out_capacity: int,
) -> tuple[Array, Array, Array]:
    """Sparse-vector semiring SpMSpV over a CSC tile.

    Args:
      x_ind: int32[xcap] active column ids; padding slots hold ids >= ncols
        (the sentinel convention — prefix position does not matter). Valid
        ids must be DISTINCT: the expansion bound below assumes each matrix
        entry is touched at most once.
      x_val: values aligned with x_ind.
      x_nnz: dynamic count of valid x entries (bookkeeping only; validity is
        decided by the sentinel, matching the SpTuples convention).
      out_capacity: static bound on distinct output rows (<= nrows).

    Returns (y_ind, y_val, y_nnz): compacted sparse output, row-sorted.

    Mirrors ``SpImpl::SpMXSpV_Bucket`` (SpImpl.cpp:390-600) but replaces the
    two-phase bucket routing with expand (column walks flattened to static
    slots) → semiring combine by destination row → compaction.
    """
    del x_nnz  # validity comes from the sentinel ids
    # Expansion capacity: with distinct active columns (precondition above),
    # every valid A entry is touched at most once → tile capacity bounds it.
    row, prod = _expand_products(sr, a_csc, x_ind, x_val, a_csc.capacity)
    y_dense = segment_reduce(sr, prod, row, a_csc.nrows)
    # Compact nonzero (≠ semiring zero) entries.
    zero = sr.zero(y_dense.dtype)
    # Only rows actually touched count — but a touched row may legitimately
    # hold the zero value only when sr.add produced it; CombBLAS stores it.
    touched = (
        jnp.zeros((a_csc.nrows,), jnp.int32)
        .at[row]
        .add(jnp.ones_like(row), mode="drop")
        > 0
    )
    keep = touched
    pos = jnp.cumsum(keep.astype(jnp.int32)) - 1
    scatter_idx = jnp.where(keep, pos, out_capacity)
    all_rows = jnp.arange(a_csc.nrows, dtype=jnp.int32)
    y_ind = (
        jnp.full((out_capacity,), a_csc.nrows, jnp.int32)
        .at[scatter_idx]
        .set(all_rows, mode="drop")
    )
    y_val = (
        jnp.full((out_capacity,), zero, y_dense.dtype)
        .at[scatter_idx]
        .set(y_dense, mode="drop")
    )
    # Clamp: rows beyond out_capacity were dropped by the scatter above, so
    # the reported count must match what the buffers actually hold.
    y_nnz = jnp.minimum(jnp.sum(keep).astype(jnp.int32), jnp.int32(out_capacity))
    return y_ind, y_val, y_nnz
