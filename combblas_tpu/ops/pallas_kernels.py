"""Pallas TPU kernels for semiring-dense hot ops.

The reference's hot loops are hand-written C++ (``mtSpGEMM.h``,
``Friends.h``); on TPU most of them map best onto XLA's native
gather/sort/reduce (see ops/ and parallel/ellmat.py). The op XLA genuinely
lacks is a fused SEMIRING dense matmul: ``C = A ⊗ B`` over (min, +) or
(max, min) has no MXU lowering, and the naive jnp formulation materializes
an [m, k, n] broadcast. This Pallas kernel tiles it like a classic blocked
GEMM — A/B blocks staged in VMEM, the contraction as an in-kernel loop of
VPU adds/mins over an accumulator — giving dense-block tropical products
for APSP-style repeated squaring and dense subproblems of semiring SpGEMM.

``plus_times`` is included for completeness (it lowers to the MXU via
jnp.dot inside the kernel). Use ``interpret=True`` on CPU (tests).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_FOLDS = {
    "min_plus": (jnp.minimum, jnp.add, jnp.inf),
    "max_plus": (jnp.maximum, jnp.add, -jnp.inf),
    "max_min": (jnp.maximum, jnp.minimum, -jnp.inf),
    "plus_times": (jnp.add, jnp.multiply, 0.0),
}


def _semiring_mm_kernel(a_ref, b_ref, o_ref, *, add, mul, zero, bk: int):
    @pl.when(pl.program_id(2) == 0)
    def _init():
        o_ref[...] = jnp.full_like(o_ref, zero)

    if (add, mul) == (jnp.add, jnp.multiply):
        o_ref[...] += jnp.dot(
            a_ref[...], b_ref[...], preferred_element_type=o_ref.dtype
        )
        return

    # Chunked static-slice contraction: each step broadcasts a [bm, CH, 1] x
    # [1, CH, bn] semiring product and folds the CH axis — static shapes
    # only (Mosaic rejects the dynamic-slice fori formulation), VMEM held to
    # bm*CH*bn floats per step.
    CH = 8
    acc = o_ref[...]
    for kk0 in range(0, bk, CH):
        a_blk = a_ref[:, kk0 : kk0 + CH]  # [bm, CH]
        b_blk = b_ref[kk0 : kk0 + CH, :]  # [CH, bn]
        prods = mul(a_blk[:, :, None], b_blk[None, :, :])  # [bm, CH, bn]
        if add is jnp.minimum:
            step = jnp.min(prods, axis=1)
        elif add is jnp.maximum:
            step = jnp.max(prods, axis=1)
        else:
            step = jnp.sum(prods, axis=1)
        acc = add(acc, step)
    o_ref[...] = acc


def semiring_matmul(
    kind: str,
    a: jax.Array,
    b: jax.Array,
    *,
    bm: int = 128,
    bk: int = 128,
    bn: int = 128,
    interpret: bool = False,
) -> jax.Array:
    """C[i,j] = ⊕_k a[i,k] ⊗ b[k,j] for ``kind`` in {min_plus, max_plus,
    max_min, plus_times}. Dims must divide by the block sizes (pad with the
    semiring zero otherwise)."""
    add, mul, zero = _FOLDS[kind]
    m, k = a.shape
    k2, n = b.shape
    assert k == k2, (a.shape, b.shape)
    assert m % bm == 0 and k % bk == 0 and n % bn == 0, (
        f"dims {(m, k, n)} must divide blocks {(bm, bk, bn)}"
    )
    grid = (m // bm, n // bn, k // bk)
    kernel = functools.partial(
        _semiring_mm_kernel, add=add, mul=mul, zero=zero, bk=bk
    )
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), a.dtype),
        interpret=interpret,
    )(a, b)


def min_plus_matmul(a, b, *, interpret: bool = False) -> jax.Array:
    """Tropical matmul — the APSP / repeated-squaring building block
    (dense-block analog of the MIN_PLUS SpGEMM)."""
    return semiring_matmul("min_plus", a, b, interpret=interpret)
