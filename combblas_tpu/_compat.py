"""JAX version compatibility gates.

The codebase targets the modern public API (``jax.shard_map`` with the
``check_vma`` kwarg). Older runtimes (<= 0.4.x, like the baked CPU test
image) only ship ``jax.experimental.shard_map.shard_map`` with the
``check_rep`` spelling. Rather than sprinkling try/except over every
call site, this module installs a thin adapter under ``jax.shard_map``
once, at package import — semantics are identical (``check_vma`` maps to
``check_rep``; both disable the replication/varying-manual-axes check).

No-op on runtimes that already provide ``jax.shard_map``.
"""

from __future__ import annotations

import jax


def install() -> None:
    if not hasattr(jax, "shard_map"):
        try:
            from jax.experimental.shard_map import shard_map as _shard_map
        except ImportError:  # nothing to adapt to; call sites fail loudly
            _shard_map = None
        if _shard_map is not None:

            def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None,
                          **kw):
                if check_vma is not None and "check_rep" not in kw:
                    kw["check_rep"] = check_vma
                return _shard_map(
                    f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
                )

            jax.shard_map = shard_map

    # Pallas-TPU params dataclass: renamed TPUCompilerParams (old) ->
    # CompilerParams (new); the kwargs we use (vmem_limit_bytes,
    # dimension_semantics) exist under both names.
    try:
        from jax.experimental.pallas import tpu as pltpu

        if not hasattr(pltpu, "CompilerParams") and hasattr(
            pltpu, "TPUCompilerParams"
        ):
            pltpu.CompilerParams = pltpu.TPUCompilerParams
    except ImportError:
        pass


install()
