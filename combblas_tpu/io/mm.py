"""Matrix Market + binary triple I/O (≈ ParallelReadMM / ParallelWriteMM /
ParallelBinaryWrite, SpParMat.cpp:3980-4218, :620-714; vector
ParallelRead/Write, FullyDistSpVec.h:148-154).

Read path: the native C++ parser (``native/mmparse.cpp``, byte-range
threaded — the FetchBatch scheme) when a toolchain is available, else a
numpy fallback. Symmetric/skew banners are expanded to full storage, like
the reference's reader.

Binary format (≈ FileHeader.h:109): 32-byte header
``b"CBTPUBIN" | uint64 nrows | uint64 ncols | uint64 nnz`` followed by
int64 rows, int64 cols, float64 vals arrays back to back.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "native")
_LIB_LOCK = threading.Lock()
_LIB = None
_LIB_FAILED = False

_MAGIC = b"CBTPUBIN"


def _load_native():
    """Build (once) and load the C++ parser; None if no toolchain."""
    global _LIB, _LIB_FAILED
    if _LIB is not None or _LIB_FAILED:
        return _LIB
    with _LIB_LOCK:
        if _LIB is not None or _LIB_FAILED:
            return _LIB
        src = os.path.join(_NATIVE_DIR, "mmparse.cpp")
        so = os.path.join(_NATIVE_DIR, "libmmparse.so")
        try:
            if not os.path.exists(so) or os.path.getmtime(so) < os.path.getmtime(src):
                subprocess.run(
                    [
                        "g++", "-O3", "-std=c++17", "-shared", "-fPIC",
                        "-pthread", src, "-o", so,
                    ],
                    check=True,
                    capture_output=True,
                )
            lib = ctypes.CDLL(so)
            lib.mm_header.restype = ctypes.c_int
            lib.mm_header.argtypes = [ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64)]
            lib.mm_parse.restype = ctypes.c_int64
            lib.mm_parse.argtypes = [
                ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_double),
                ctypes.c_int64,
                ctypes.c_int,
            ]
            _LIB = lib
        except Exception:
            _LIB_FAILED = True
            _LIB = None
    return _LIB


def _read_mm_python(path):
    """Pure-python fallback parser (header + body).

    Handles both ``coordinate`` (sparse) and ``array`` (dense,
    column-major — ``src/mmio.c:60-70`` banner branch) formats; the dense
    body is converted to COO triplets of its NONZERO entries (this is a
    sparse library — explicit zeros in an array file carry no structure).
    """
    with open(path, "rb") as f:
        banner = f.readline().decode()
        assert banner.startswith("%%MatrixMarket"), f"not MatrixMarket: {path}"
        b = banner.lower()
        dense = "array" in b
        assert dense or "coordinate" in b, f"unknown MM format: {banner!r}"
        pattern = "pattern" in b
        assert not (dense and pattern), "array+pattern is invalid MatrixMarket"
        sym = (
            2 if "skew-symmetric" in b else 1 if "symmetric" in b
            else 3 if "hermitian" in b else 0
        )
        line = f.readline().decode()
        while line.startswith("%"):
            line = f.readline().decode()
        if dense:
            nrows, ncols = (int(x) for x in line.split()[:2])
            body = np.loadtxt(f, dtype=np.float64, ndmin=1).reshape(-1)
            if sym in (1, 2, 3):
                # packed lower triangle (incl. diagonal), column-major
                assert nrows == ncols, "symmetric array must be square"
                r_t, c_t = np.tril_indices(nrows)
                order = np.lexsort((r_t, c_t))  # column-major packing
                full = np.zeros((nrows, ncols), np.float64)
                full[r_t[order], c_t[order]] = body
            else:
                full = body.reshape((ncols, nrows)).T  # column-major
            rows, cols = np.nonzero(full)
            vals = full[rows, cols]
            return (rows.astype(np.int64), cols.astype(np.int64), vals,
                    nrows, ncols, sym)
        nrows, ncols, nnz = (int(x) for x in line.split()[:3])
        if pattern:
            data = np.loadtxt(f, dtype=np.int64, usecols=(0, 1), ndmin=2)
            rows, cols = data[:, 0] - 1, data[:, 1] - 1
            vals = np.ones(len(rows), np.float64)
        else:
            data = np.loadtxt(f, dtype=np.float64, usecols=(0, 1, 2), ndmin=2)
            rows = data[:, 0].astype(np.int64) - 1
            cols = data[:, 1].astype(np.int64) - 1
            vals = data[:, 2]
    return rows, cols, vals, nrows, ncols, sym


def read_mm(path, *, expand_symmetric: bool = True, nthreads: int | None = None):
    """Parse a Matrix Market coordinate file.

    Returns (rows, cols, vals, nrows, ncols): int64/int64/float64 arrays with
    symmetric/skew storage expanded to full (off-diagonal mirrored, negated
    for skew) when ``expand_symmetric``.
    """
    lib = _load_native()
    if lib is not None:
        hdr = (ctypes.c_int64 * 6)()
        rc = lib.mm_header(path.encode(), hdr)
        if rc == 4:
            # native parser is coordinate-only; dense "array" files take
            # the python path (mmio.c:60-70 parity)
            lib = None
        elif rc != 0:
            raise ValueError(f"mm_header failed ({rc}) for {path}")
    if lib is not None:
        nrows, ncols, nnz, _pattern, sym, _integer = (int(x) for x in hdr)
        rows = np.empty(max(nnz, 1), np.int64)
        cols = np.empty(max(nnz, 1), np.int64)
        vals = np.empty(max(nnz, 1), np.float64)
        nt = nthreads or min(os.cpu_count() or 1, 16)
        got = lib.mm_parse(
            path.encode(),
            rows.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            cols.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            vals.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            len(rows),
            nt,
        )
        if got < 0:
            raise ValueError(f"mm_parse failed ({got}) for {path}")
        rows, cols, vals = rows[:got], cols[:got], vals[:got]
    else:
        rows, cols, vals, nrows, ncols, sym = _read_mm_python(path)

    if expand_symmetric and sym:
        rows, cols, vals = _expand_symmetric(rows, cols, vals, sym)
    return rows, cols, vals, nrows, ncols


def read_mm_spmat(grid, path, dtype=np.float32, dedup_sr=None, **kw):
    """read_mm → SpParMat on ``grid`` (the ParallelReadMM equivalent)."""
    from ..parallel.spmat import SpParMat

    rows, cols, vals, nrows, ncols = read_mm(path, **kw)
    return SpParMat.from_global_coo(
        grid, rows, cols, vals.astype(dtype), nrows, ncols, dedup_sr=dedup_sr
    )


def _expand_symmetric(rows, cols, vals, sym):
    """Mirror off-diagonal entries for symmetric (1) / skew (2) /
    hermitian-as-real (3) banners."""
    off = rows != cols
    mr, mc = cols[off], rows[off]
    mv = -vals[off] if sym == 2 else vals[off]
    return (
        np.concatenate([rows, mr]),
        np.concatenate([cols, mc]),
        np.concatenate([vals, mv]),
    )


def _mm_header_span(path):
    """(data_offset, nrows, ncols, nnz, pattern, sym) — the byte offset of
    the first data line plus the parsed size header."""
    with open(path, "rb") as f:
        banner = f.readline().decode()
        assert banner.startswith("%%MatrixMarket"), f"not MatrixMarket: {path}"
        b = banner.lower()
        assert "coordinate" in b, "only coordinate (sparse) format supported"
        pattern = "pattern" in b
        sym = (
            2 if "skew-symmetric" in b else 1 if "symmetric" in b
            else 3 if "hermitian" in b else 0
        )
        line = f.readline().decode()
        while line.startswith("%"):
            line = f.readline().decode()
        nrows, ncols, nnz = (int(x) for x in line.split()[:3])
        return f.tell(), nrows, ncols, nnz, pattern, sym


def read_mm_distributed(
    grid, path, dtype=np.float32, *, expand_symmetric: bool = True,
    dedup_sr=None,
):
    """Multi-PROCESS Matrix Market read: each controller parses only its
    byte range of the data section, then one on-device two-hop all_to_all
    routes every tuple to its owner tile.

    The reference's ``ParallelReadMM`` (SpParMat.cpp:3980-4127) splits the
    file into per-rank byte ranges with the usual newline rule (a range
    owns a line iff the line STARTS inside it) and exchanges tuples with
    Alltoallv; this is the same protocol with processes in place of ranks
    and ``redistribute_coo`` in place of MPI. Single-process, it
    degenerates to a plain read + device-side distribution.

    Returns an SpParMat on ``grid`` (which must span the global devices).
    """
    import jax

    from ..parallel.redistribute import from_device_coo

    data_off, nrows, ncols, _nnz, pattern, sym = _mm_header_span(path)
    nproc = jax.process_count()
    me = jax.process_index()
    if nproc == 1:
        # degenerate case: the native threaded parser reads the whole
        # file; only the device-side distribution tail differs
        rows, cols, vals, nrows, ncols = read_mm(
            path, expand_symmetric=expand_symmetric
        )
    else:
        fsize = os.path.getsize(path)
        span = fsize - data_off
        lo = data_off + (span * me) // nproc
        hi = data_off + (span * (me + 1)) // nproc

        with open(path, "rb") as f:
            # newline rule: a range owns a line iff the line STARTS inside
            # it. Skip a partial first line (the previous range owns it);
            # when no line starts in the range at all (start >= hi) the
            # range owns nothing — reading on would duplicate another
            # range's lines.
            if me > 0:
                f.seek(lo - 1)
                f.readline()
                start = f.tell()
            else:
                start = lo
                f.seek(start)
            buf = f.read(max(hi - start, 0))
            if buf and not buf.endswith(b"\n") and hi < fsize:
                buf += f.readline()

        import io as _io

        if len(buf.strip()) == 0:
            rows = np.empty(0, np.int64)
            cols = np.empty(0, np.int64)
            vals = np.empty(0, np.float64)
        elif pattern:
            data = np.loadtxt(
                _io.BytesIO(buf), dtype=np.int64, usecols=(0, 1), ndmin=2
            )
            rows, cols = data[:, 0] - 1, data[:, 1] - 1
            vals = np.ones(len(rows), np.float64)
        else:
            data = np.loadtxt(
                _io.BytesIO(buf), dtype=np.float64, usecols=(0, 1, 2),
                ndmin=2,
            )
            rows = data[:, 0].astype(np.int64) - 1
            cols = data[:, 1].astype(np.int64) - 1
            vals = data[:, 2]

        if expand_symmetric and sym:
            rows, cols, vals = _expand_symmetric(rows, cols, vals, sym)

    # My slice of the GRID's devices (a grid may use fewer devices than
    # the process owns — chunking must follow the grid, not
    # local_device_count, or entries past grid_devs*chunk never ship)
    import jax.numpy as jnp

    mesh = grid.mesh
    darr = mesh.devices  # [pr, pc] device array
    myslices = {}
    k = 0
    for i in range(darr.shape[0]):
        for j in range(darr.shape[1]):
            if darr[i, j].process_index == me:
                myslices[(i, j)] = k
                k += 1
    assert k > 0, "grid has no devices on this process (see make_global_grid)"
    nmine = k

    # agree on a global per-device chunk (shapes must match SPMD-wide)
    my_chunk = -(-len(rows) // nmine)
    if nproc > 1:
        from jax.experimental import multihost_utils

        chunks = multihost_utils.process_allgather(
            jnp.asarray([my_chunk], jnp.int32)
        ).reshape(-1)
        chunk = int(np.max(chunks))
    else:
        chunk = my_chunk
    chunk = max(chunk, 1)

    # pad my entries to [nmine, chunk] (sentinel row = nrows: dropped)
    pr_ = np.full((nmine * chunk,), nrows, np.int64)
    pc_ = np.full((nmine * chunk,), ncols, np.int64)
    pv_ = np.zeros((nmine * chunk,), np.float64)
    pr_[: len(rows)], pc_[: len(rows)], pv_[: len(rows)] = rows, cols, vals

    def build(arr, dt):
        full_shape = (darr.shape[0], darr.shape[1], chunk)
        sharding = grid.tile_sharding()

        def cb(index):
            # index selects one (i, j) tile slice of the global array
            i = index[0].start or 0
            j = index[1].start or 0
            s = myslices[(i, j)]
            return np.ascontiguousarray(
                arr[s * chunk : (s + 1) * chunk].astype(dt)
            ).reshape(1, 1, chunk)

        return jax.make_array_from_callback(full_shape, sharding, cb)

    gr = build(pr_, np.int32)
    gc = build(pc_, np.int32)
    gv = build(pv_, dtype)
    return from_device_coo(
        grid, gr, gc, gv, nrows, ncols, dedup_sr=dedup_sr
    )


def write_mm(path, mat, *, comment: str | None = None):
    """Write an SpParMat (or (rows, cols, vals, nrows, ncols)) as MM
    coordinate real general — the ``ParallelWriteMM`` equivalent."""
    if hasattr(mat, "to_global_coo"):
        rows, cols, vals = mat.to_global_coo()
        nrows, ncols = mat.nrows, mat.ncols
    else:
        rows, cols, vals, nrows, ncols = mat
    order = np.lexsort((rows, cols))  # column-major like the reference
    rows, cols, vals = rows[order], cols[order], vals[order]
    with open(path, "w") as f:
        f.write("%%MatrixMarket matrix coordinate real general\n")
        if comment:
            for ln in comment.splitlines():
                f.write(f"% {ln}\n")
        f.write(f"{nrows} {ncols} {len(rows)}\n")
    with open(path, "ab") as f:  # vectorized body append
        np.savetxt(
            f,
            np.column_stack(
                [rows + 1, cols + 1, np.asarray(vals, np.float64)]
            ),
            fmt="%d %d %.10g",
        )


def write_binary(path, mat):
    """Raw binary triple dump (≈ ParallelBinaryWrite, SpParMat.cpp:620-714)."""
    if hasattr(mat, "to_global_coo"):
        rows, cols, vals = mat.to_global_coo()
        nrows, ncols = mat.nrows, mat.ncols
    else:
        rows, cols, vals, nrows, ncols = mat
    with open(path, "wb") as f:
        f.write(_MAGIC)
        np.array([nrows, ncols, len(rows)], np.uint64).tofile(f)
        rows.astype(np.int64).tofile(f)
        cols.astype(np.int64).tofile(f)
        vals.astype(np.float64).tofile(f)


def read_binary(path):
    """Inverse of ``write_binary`` → (rows, cols, vals, nrows, ncols)."""
    with open(path, "rb") as f:
        assert f.read(8) == _MAGIC, f"bad magic in {path}"
        nrows, ncols, nnz = (int(x) for x in np.fromfile(f, np.uint64, 3))
        rows = np.fromfile(f, np.int64, nnz)
        cols = np.fromfile(f, np.int64, nnz)
        vals = np.fromfile(f, np.float64, nnz)
    return rows, cols, vals, nrows, ncols


def write_vec(path, vec, active=None):
    """Text "index value" dump of a DistVec (≈ FullyDistSpVec::ParallelWrite
    with 1-based ids). ``active`` (bool DistVec) selects a sparse subset."""
    x = vec.to_global()
    mask = (
        np.asarray(active.to_global(), bool)
        if active is not None
        else np.ones(len(x), bool)
    )
    with open(path, "w") as f:
        f.write(f"{len(x)} {int(mask.sum())}\n")
        for i in np.nonzero(mask)[0]:
            f.write(f"{i + 1} {x[i]}\n")


def read_vec(grid, path, dtype=np.float32, align="row", fill=0):
    """Inverse of ``write_vec`` → (DistVec, active bool DistVec)."""
    from ..parallel.vec import DistVec

    with open(path) as f:
        n, _nnz = (int(t) for t in f.readline().split()[:2])
        vals = np.full(n, fill, dtype)
        mask = np.zeros(n, bool)
        for line in f:
            parts = line.split()
            if len(parts) < 2:
                continue
            raw = int(parts[0])
            if not (1 <= raw <= n):  # 1-based ids; reject instead of wrapping
                raise ValueError(
                    f"vector index {raw} out of range 1..{n} in {path}"
                )
            tok = parts[1]
            # Parse numerically first: np.bool_("False") is True (any
            # non-empty string is truthy), which silently corrupted bool
            # round-trips through write_vec.
            if tok in ("True", "False"):
                v = tok == "True"
            else:
                try:
                    v = int(tok)  # exact for int64-range values
                except ValueError:
                    v = float(tok)
                    if np.issubdtype(vals.dtype, np.integer):
                        # Keep the old loud failure: silently truncating
                        # 3.7 -> 3 into an int vector corrupts data.
                        raise ValueError(
                            f"non-integer value {tok!r} for integer dtype "
                            f"{vals.dtype} in {path}"
                        )
            vals[raw - 1] = v
            mask[raw - 1] = True
    return (
        DistVec.from_global(grid, vals, align=align, fill=fill),
        DistVec.from_global(grid, mask, align=align, fill=False),
    )
