// Native Matrix Market parser — the performance path of combblas_tpu's I/O.
//
// Plays the role of the reference's C mmio + parallel text ingestion
// (src/mmio.c banner/size parsing; SpParHelper::FetchBatch byte-range
// splitting with line realignment, SpParHelper.h:110-111, used by
// SpParMat::ParallelReadMM, SpParMat.cpp:3980-4127).  Where the reference
// parallelizes across MPI ranks reading one shared file, a TPU host
// parallelizes across threads: the body is split into nthreads byte ranges,
// each realigned to the next newline, counted, then parsed in place.
//
// C ABI (ctypes-friendly), no Python headers needed.

#include <cctype>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Header {
    int64_t nrows = 0, ncols = 0, nnz = 0;
    bool pattern = false;   // no value column
    bool complex_ = false;  // two value columns (we keep the real part)
    bool integer_ = false;
    int sym = 0;            // 0 general, 1 symmetric, 2 skew, 3 hermitian
    int64_t body_offset = 0;
};

// Parse the banner + size line; returns 0 on success.
int parse_header(FILE* f, Header* h) {
    char line[4096];
    if (!fgets(line, sizeof line, f)) return 1;
    if (strncmp(line, "%%MatrixMarket", 14) != 0) return 2;
    std::string banner(line);
    for (auto& ch : banner) ch = (char)tolower((unsigned char)ch);
    if (banner.find("matrix") == std::string::npos) return 3;
    if (banner.find("coordinate") == std::string::npos) return 4;  // dense unsupported here
    h->pattern = banner.find("pattern") != std::string::npos;
    h->complex_ = banner.find("complex") != std::string::npos;
    h->integer_ = banner.find("integer") != std::string::npos;
    if (banner.find("skew-symmetric") != std::string::npos) h->sym = 2;
    else if (banner.find("symmetric") != std::string::npos) h->sym = 1;
    else if (banner.find("hermitian") != std::string::npos) h->sym = 3;
    // skip comment lines
    long pos;
    for (;;) {
        pos = ftell(f);
        if (!fgets(line, sizeof line, f)) return 5;
        if (line[0] != '%') break;
    }
    long long a, b, c;
    if (sscanf(line, "%lld %lld %lld", &a, &b, &c) != 3) return 6;
    h->nrows = a; h->ncols = b; h->nnz = c;
    h->body_offset = ftell(f);
    return 0;
}

// Parse one byte range [s, e) of the body buffer into out arrays starting at
// slot `slot`. Returns number of entries parsed.
int64_t parse_range(const char* buf, int64_t s, int64_t e, bool pattern,
                    int64_t* rows, int64_t* cols, double* vals,
                    int64_t slot, int64_t cap) {
    const char* p = buf + s;
    const char* end = buf + e;
    int64_t k = slot;
    while (p < end && k < cap) {
        // skip whitespace/newlines
        while (p < end && isspace((unsigned char)*p)) ++p;
        if (p >= end) break;
        char* q;
        long long r = strtoll(p, &q, 10);
        if (q == p) { while (p < end && *p != '\n') ++p; continue; }
        p = q;
        long long c = strtoll(p, &q, 10);
        if (q == p) { while (p < end && *p != '\n') ++p; continue; }
        p = q;
        double v = 1.0;
        if (!pattern) {
            v = strtod(p, &q);
            p = q;
        }
        // skip rest of line (imaginary part of complex, stray columns)
        while (p < end && *p != '\n') ++p;
        rows[k] = r - 1;  // MM is 1-based
        cols[k] = c - 1;
        vals[k] = v;
        ++k;
    }
    return k - slot;
}

}  // namespace

extern "C" {

// Returns 0 on success. out = [nrows, ncols, nnz, pattern, sym, integer].
int mm_header(const char* path, int64_t* out) {
    FILE* f = fopen(path, "rb");
    if (!f) return -1;
    Header h;
    int rc = parse_header(f, &h);
    fclose(f);
    if (rc) return rc;
    out[0] = h.nrows; out[1] = h.ncols; out[2] = h.nnz;
    out[3] = h.pattern ? 1 : 0; out[4] = h.sym; out[5] = h.integer_ ? 1 : 0;
    return 0;
}

// Parse the whole body with `nthreads` threads into caller-allocated arrays
// of capacity `cap`. Returns entries parsed, or negative on error.
int64_t mm_parse(const char* path, int64_t* rows, int64_t* cols, double* vals,
                 int64_t cap, int nthreads) {
    FILE* f = fopen(path, "rb");
    if (!f) return -1;
    Header h;
    if (parse_header(f, &h)) { fclose(f); return -2; }
    fseek(f, 0, SEEK_END);
    int64_t fsize = ftell(f);
    int64_t bodylen = fsize - h.body_offset;
    // +1 NUL terminator: strtoll/strtod are unbounded, so a final token with
    // no trailing newline must hit '\0', not run off the allocation.
    std::vector<char> buf((size_t)bodylen + 1, '\0');
    fseek(f, h.body_offset, SEEK_SET);
    if (bodylen > 0 &&
        fread(buf.data(), 1, (size_t)bodylen, f) != (size_t)bodylen) {
        fclose(f);
        return -3;
    }
    fclose(f);
    if (nthreads < 1) nthreads = 1;

    // Byte-range split with newline realignment (the FetchBatch scheme).
    std::vector<int64_t> starts(nthreads + 1);
    starts[0] = 0;
    starts[nthreads] = bodylen;
    for (int t = 1; t < nthreads; ++t) {
        int64_t guess = bodylen * t / nthreads;
        while (guess < bodylen && buf[(size_t)guess] != '\n') ++guess;
        starts[t] = guess < bodylen ? guess + 1 : bodylen;
    }
    // Count entries (newline-terminated non-empty lines) per range so each
    // thread writes to a disjoint slice.
    std::vector<int64_t> counts(nthreads, 0);
    {
        std::vector<std::thread> th;
        for (int t = 0; t < nthreads; ++t) {
            th.emplace_back([&, t] {
                int64_t n = 0;
                const char* p = buf.data() + starts[t];
                const char* end = buf.data() + starts[t + 1];
                while (p < end) {
                    while (p < end && isspace((unsigned char)*p)) ++p;
                    if (p >= end) break;
                    ++n;
                    while (p < end && *p != '\n') ++p;
                }
                counts[t] = n;
            });
        }
        for (auto& x : th) x.join();
    }
    std::vector<int64_t> offs(nthreads + 1, 0);
    for (int t = 0; t < nthreads; ++t) offs[t + 1] = offs[t] + counts[t];
    if (offs[nthreads] > cap) return -4;  // caller's buffer too small

    std::vector<int64_t> parsed(nthreads, 0);
    {
        std::vector<std::thread> th;
        for (int t = 0; t < nthreads; ++t) {
            th.emplace_back([&, t] {
                parsed[t] = parse_range(buf.data(), starts[t], starts[t + 1],
                                        h.pattern, rows, cols, vals, offs[t],
                                        offs[t] + counts[t]);
            });
        }
        for (auto& x : th) x.join();
    }
    int64_t total = 0;
    for (int t = 0; t < nthreads; ++t) total += parsed[t];
    // Compact if any range parsed fewer than counted (malformed lines).
    if (total != offs[nthreads]) {
        int64_t w = 0;
        for (int t = 0; t < nthreads; ++t) {
            int64_t s = offs[t];
            for (int64_t k = 0; k < parsed[t]; ++k, ++w) {
                rows[w] = rows[s + k];
                cols[w] = cols[s + k];
                vals[w] = vals[s + k];
            }
        }
    }
    return total;
}

}  // extern "C"
