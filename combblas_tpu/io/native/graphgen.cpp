// Native Graph500 v2.1 deterministic Kronecker edge generator.
//
// The reference's generator is native C (graph500-1.2/generator/, driven
// by RefGen21.h); this is the framework's native twin of
// combblas_tpu/utils/refgen21.py — identical MRG-over-Z_{2^31-1} stream,
// leapfrog skip matrices (recomputed at init), 4-way Bernoulli with exact
// rejection, clip-and-flip, and the multiplicative bit-reverse scramble.
// Bit-for-bit equal to the Python implementation (tested) and to the
// reference generator's output (the Python side carries the golden tests).
//
// C ABI (ctypes): cbtpu_graph500_edges(userseed, logN, start, end,
// src_out, dst_out, nthreads) — any sub-range of the global stream,
// threaded over edges (each edge's state is an O(log ei) skip from the
// seed, so threads are independent — the same property the reference's
// OpenMP loop exploits).
//
// Build: g++ -O2 -shared -fPIC -o libgraphgen.so graphgen.cpp -lpthread

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

constexpr uint64_t P = 0x7FFFFFFFull;  // 2^31 - 1
constexpr uint64_t X = 107374182ull;
constexpr uint64_t Y = 104480ull;
constexpr int A_NUM = 5700;
constexpr int BC_NUM = 1900;
constexpr uint32_t DENOM = 10000;
constexpr uint32_t REJECT_LIMIT = 0xFFFFFFFFu % DENOM;  // 7295

inline uint64_t mod(uint64_t a) { return a % P; }
inline uint64_t mmul(uint64_t a, uint64_t b) { return (a * b) % P; }

struct Mat {
  uint64_t s, t, u, v, w, a, b, c, d;
  void cache() {
    a = mod(X * s + t);
    b = mod(X * a + u);
    c = mod(X * b + v);
    d = mod(X * c + w);
  }
};

Mat identity_mat() {
  Mat m{0, 0, 0, 0, 1, 0, 0, 0, 0};
  m.cache();
  return m;
}

Mat A_mat() {
  Mat m{0, 0, 0, 1, 0, 0, 0, 0, 0};
  m.cache();
  return m;
}

Mat mat_mul(const Mat& m, const Mat& n) {
  Mat r;
  r.s = mod(mmul(m.s, n.d) + mmul(m.t, n.c) + mmul(m.u, n.b) +
            mmul(m.v, n.a) + mmul(m.w, n.s));
  r.t = mod(mmul(mmul(m.s, n.s), Y) + mmul(m.t, n.w) + mmul(m.u, n.v) +
            mmul(m.v, n.u) + mmul(m.w, n.t));
  r.u = mod(mmul(mod(mmul(m.s, n.a) + mmul(m.t, n.s)), Y) + mmul(m.u, n.w) +
            mmul(m.v, n.v) + mmul(m.w, n.u));
  r.v = mod(mmul(mod(mmul(m.s, n.b) + mmul(m.t, n.a) + mmul(m.u, n.s)), Y) +
            mmul(m.v, n.w) + mmul(m.w, n.v));
  r.w = mod(mmul(mod(mmul(m.s, n.c) + mmul(m.t, n.b) + mmul(m.u, n.a) +
                     mmul(m.v, n.s)), Y) +
            mmul(m.w, n.w));
  r.cache();
  return r;
}

struct State {
  uint64_t z1, z2, z3, z4, z5;
};

inline void apply(const Mat& m, State& st) {
  uint64_t o1 = mod(mmul(m.d, st.z1) +
                    mmul(mod(mmul(m.s, st.z2) + mmul(m.a, st.z3) +
                             mmul(m.b, st.z4) + mmul(m.c, st.z5)),
                         Y));
  uint64_t o2 = mod(mod(mmul(m.c, st.z1) + mmul(m.w, st.z2)) +
                    mmul(mod(mmul(m.s, st.z3) + mmul(m.a, st.z4) +
                             mmul(m.b, st.z5)),
                         Y));
  uint64_t o3 = mod(mod(mmul(m.b, st.z1) + mmul(m.v, st.z2) +
                        mmul(m.w, st.z3)) +
                    mmul(mod(mmul(m.s, st.z4) + mmul(m.a, st.z5)), Y));
  uint64_t o4 = mod(mod(mmul(m.a, st.z1) + mmul(m.u, st.z2) +
                        mmul(m.v, st.z3) + mmul(m.w, st.z4)) +
                    mmul(mmul(m.s, st.z5), Y));
  uint64_t o5 = mod(mmul(m.s, st.z1) + mmul(m.t, st.z2) + mmul(m.u, st.z3) +
                    mmul(m.v, st.z4) + mmul(m.w, st.z5));
  st = {o1, o2, o3, o4, o5};
}

// skip table: A^(256^i * j), i < 24, j < 256
struct SkipTable {
  Mat m[24][256];
  SkipTable() {
    Mat base = A_mat();
    for (int i = 0; i < 24; ++i) {
      Mat cur = identity_mat();
      m[i][0] = cur;
      for (int j = 1; j < 256; ++j) {
        cur = mat_mul(cur, base);
        m[i][j] = cur;
      }
      base = mat_mul(cur, base);
    }
  }
};

const SkipTable& table() {
  static SkipTable t;
  return t;
}

inline void skip(State& st, uint64_t high, uint64_t middle, uint64_t low) {
  const SkipTable& tab = table();
  for (int bi = 0; low; ++bi, low >>= 8) {
    uint8_t v = low & 0xFF;
    if (v) apply(tab.m[bi][v], st);
  }
  for (int bi = 8; middle; ++bi, middle >>= 8) {
    uint8_t v = middle & 0xFF;
    if (v) apply(tab.m[bi][v], st);
  }
  for (int bi = 16; high; ++bi, high >>= 8) {
    uint8_t v = high & 0xFF;
    if (v) apply(tab.m[bi][v], st);
  }
}

inline uint32_t get_uint_orig(State& st) {
  uint64_t ne = mod(X * st.z1 + Y * st.z5);
  st = {ne, st.z1, st.z2, st.z3, st.z4};
  return (uint32_t)ne;
}

inline int bernoulli4(State& st) {
  uint32_t val = get_uint_orig(st);
  while (val < REJECT_LIMIT) val = get_uint_orig(st);
  val %= DENOM;
  if ((int)val < BC_NUM) return 1;
  val -= BC_NUM;
  if ((int)val < BC_NUM) return 2;
  val -= BC_NUM;
  if (val < (uint32_t)A_NUM) return 0;
  return 3;
}

inline uint64_t bitreverse(uint64_t x) {
  x = __builtin_bswap64(x);
  x = ((x >> 4) & 0x0F0F0F0F0F0F0F0Full) | ((x & 0x0F0F0F0F0F0F0F0Full) << 4);
  x = ((x >> 2) & 0x3333333333333333ull) | ((x & 0x3333333333333333ull) << 2);
  x = ((x >> 1) & 0x5555555555555555ull) | ((x & 0x5555555555555555ull) << 1);
  return x;
}

inline int64_t scramble(int64_t v0, int lgN, uint64_t val0, uint64_t val1) {
  uint64_t v = (uint64_t)v0;
  v += val0 + val1;
  v *= (val0 | 0x4519840211493211ull);
  v = bitreverse(v) >> (64 - lgN);
  v *= (val1 | 0x3050852102C843A5ull);
  v = bitreverse(v) >> (64 - lgN);
  return (int64_t)v;
}

}  // namespace

extern "C" int cbtpu_graph500_edges(uint64_t userseed, int logN,
                                    int64_t start_edge, int64_t end_edge,
                                    int64_t* src_out, int64_t* dst_out,
                                    int nthreads) {
  if (logN < 1 || logN > 48 || end_edge < start_edge) return 1;
  // make_mrg_seed(userseed, userseed)
  State seed;
  seed.z1 = (userseed & 0x3FFFFFFFull) + 1;
  seed.z2 = ((userseed >> 30) & 0x3FFFFFFFull) + 1;
  seed.z3 = (userseed & 0x3FFFFFFFull) + 1;
  seed.z4 = ((userseed >> 30) & 0x3FFFFFFFull) + 1;
  seed.z5 = ((userseed >> 60) << 4) + (userseed >> 60) + 1;

  // MakeScrambleValues
  State zs = seed;
  skip(zs, 50, 7, 0);
  uint64_t v0a = get_uint_orig(zs), v0b = get_uint_orig(zs);
  uint64_t v1a = get_uint_orig(zs), v1b = get_uint_orig(zs);
  uint64_t val0 = v0a * 0xFFFFFFFFull + v0b;
  uint64_t val1 = v1a * 0xFFFFFFFFull + v1b;

  int64_t total = end_edge - start_edge;
  if (nthreads < 1) nthreads = 1;
  int64_t chunk = (total + nthreads - 1) / nthreads;
  (void)table();  // build once before threading

  auto worker = [&](int64_t lo, int64_t hi) {
    for (int64_t k = lo; k < hi; ++k) {
      int64_t ei = start_edge + k;
      State st = seed;
      skip(st, 0, (uint64_t)ei, 0);
      int64_t nverts = (int64_t)1 << logN;
      int64_t bs = 0, bt = 0;
      while (nverts > 1) {
        int sq = bernoulli4(st);
        int so = sq / 2, to = sq % 2;
        if (bs == bt && so > to) {
          int tmp = so;
          so = to;
          to = tmp;
        }
        nverts /= 2;
        bs += nverts * so;
        bt += nverts * to;
      }
      src_out[k] = scramble(bs, logN, val0, val1);
      dst_out[k] = scramble(bt, logN, val0, val1);
    }
  };

  std::vector<std::thread> threads;
  for (int t = 0; t < nthreads; ++t) {
    int64_t lo = t * chunk;
    int64_t hi = lo + chunk < total ? lo + chunk : total;
    if (lo >= hi) break;
    threads.emplace_back(worker, lo, hi);
  }
  for (auto& th : threads) th.join();
  return 0;
}
