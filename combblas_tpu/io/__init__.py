"""I/O subsystem (≈ SURVEY §2.4): Matrix Market, binary triples, vector I/O.

The reference's I/O layer is native (C ``mmio.c`` + MPI-parallel byte-range
text ingestion, ``SpParMat::ParallelReadMM`` SpParMat.cpp:3980-4127). Here
the performance path is a C++ multithreaded parser (``native/mmparse.cpp``)
loaded via ctypes — built on first use with g++ — with a pure-Python
fallback so the package works without a toolchain.
"""

from .mm import (
    read_mm,
    read_mm_distributed,
    read_mm_spmat,
    write_mm,
    read_binary,
    write_binary,
    read_vec,
    write_vec,
)

__all__ = [
    "read_mm",
    "read_mm_distributed",
    "read_mm_spmat",
    "write_mm",
    "read_binary",
    "write_binary",
    "read_vec",
    "write_vec",
]
