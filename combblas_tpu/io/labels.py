"""String-labeled tuple ingestion (≈ ReadGeneralizedTuples).

The reference reads "label1 label2 [value]" triples (e.g. HipMCL protein
networks), hashes the labels (``hash.cpp`` MurmurHash), performs a
distributed relabeling to dense integer ids, and returns the permutation
alongside the matrix (``SpParMat.h:286-287``, ``TupleRead1stPassNExchange``).
Host counterpart: stable first-appearance interning (the role the
hash+exchange plays), returning (matrix, labels list, label→id dict).
"""

from __future__ import annotations

import numpy as np


def read_labeled_tuples(path, *, default_value: float = 1.0):
    """Parse "src dst [weight]" lines with string vertex labels.

    Returns (rows, cols, vals, labels): integer ids are assigned by first
    appearance (deterministic for a given file — the analog of the
    reference's deterministic relabeling), ``labels[i]`` is the string for
    id i.
    """
    ids: dict[str, int] = {}
    rows, cols, vals = [], [], []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            parts = line.split()
            if not parts or parts[0].startswith(("%", "#")):
                continue
            if len(parts) < 2:
                raise ValueError(
                    f"{path}:{lineno}: expected 'src dst [weight]', "
                    f"got {line.strip()!r}"
                )
            a, b = parts[0], parts[1]
            w = float(parts[2]) if len(parts) > 2 else default_value
            ia = ids.setdefault(a, len(ids))
            ib = ids.setdefault(b, len(ids))
            rows.append(ia)
            cols.append(ib)
            vals.append(w)
    labels = [None] * len(ids)
    for s, i in ids.items():
        labels[i] = s
    return (
        np.asarray(rows, np.int64),
        np.asarray(cols, np.int64),
        np.asarray(vals, np.float64),
        labels,
    )


def read_labeled_spmat(grid, path, dtype=np.float32, symmetrize=False,
                       dedup_sr=None):
    """read_labeled_tuples → (SpParMat over ``grid``, labels).

    ``symmetrize`` mirrors each edge (the HipMCL default for undirected
    protein networks, MCL.cpp's -I handling).
    """
    from ..parallel.spmat import SpParMat

    rows, cols, vals, labels = read_labeled_tuples(path)
    n = len(labels)
    if symmetrize:
        # Mirror off-diagonal edges, but DROP mirrored copies whose
        # coordinate already appears in the file (files often list both
        # directions; blindly mirroring would double those weights). Only
        # mirror-induced duplicates are dropped — genuine same-direction
        # multi-edges still reach ``dedup_sr`` untouched.
        orig_keys = np.unique(rows * np.int64(n) + cols)
        off = rows != cols
        mr, mc, mv = cols[off], rows[off], vals[off]
        fresh = ~np.isin(mr * np.int64(n) + mc, orig_keys)
        rows = np.concatenate([rows, mr[fresh]])
        cols = np.concatenate([cols, mc[fresh]])
        vals = np.concatenate([vals, mv[fresh]])
    A = SpParMat.from_global_coo(
        grid, rows, cols, vals.astype(dtype), n, n, dedup_sr=dedup_sr
    )
    return A, labels
