"""Semantic (attributed-edge) graphs + runtime edge filters.

The reference attaches a payload struct to every edge (``TwitterEdge.h:15-46``
— follower count, retweet flag, latest-retweet timestamp), runs BFS/MIS with
a runtime predicate over it (``FilteredBFS.cpp``, ``FilteredMIS.cpp``), and
offers two execution modes benchmarked against each other: materialize a
filtered copy once, or filter on the fly inside the semiring via the
``returnedSAID()`` do-not-store sentinel (``Semirings.h:36-49``).

TPU-native design: attributes are a struct-of-arrays — one ``[pr, pc, cap]``
array per field, slot-aligned with the structure matrix's tuples — so a
predicate is one fused elementwise op over the attribute arrays:

* ``materialize(pred)`` compacts passing entries into a plain SpParMat
  (the reference's materialized mode);
* ``mask(pred)`` keeps the layout and writes pred as 0/1 values, paired
  with a value-aware semiring (``filtered_select2nd_max``) whose ``mul``
  returns the additive identity on masked-out edges — the structural
  counterpart of returnedSAID, with zero data movement per filter change.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .semiring import Semiring, _minval
from .parallel.grid import Grid
from .parallel.spmat import SpParMat, TILE_SPEC
from .parallel.vec import DistVec

Array = jax.Array


def _sel_zero(dt):
    return -1 if jnp.issubdtype(jnp.dtype(dt), jnp.signedinteger) else _minval(dt)


#: Value-aware BFS semiring: like SELECT2ND_MAX but an edge with value 0
#: transmits nothing — the on-the-fly filter path (≈ the filtered semiring
#: over TwitterEdge, FilteredBFS.cpp's on-the-fly mode). The masked branch
#: returns the additive identity OF X'S DTYPE so mul(a, zero) == zero holds
#: for every value type, not just int32 parent ids.
FILTERED_SELECT2ND_MAX = Semiring(
    name="filtered_select2nd_max",
    add=jnp.maximum,
    mul=lambda a, x: jnp.where(a != 0, x, _sel_zero(jnp.asarray(x).dtype)),
    zero_fn=_sel_zero,
    one_fn=None,
    add_kind="max",
)


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["structure", "attrs"],
    meta_fields=[],
)
@dataclasses.dataclass(frozen=True)
class SemanticGraph:
    """Structure matrix + slot-aligned attribute arrays.

    ``attrs``: dict field-name → [pr, pc, cap] array, aligned with
    ``structure``'s tuple slots (≈ SpParMat<.., TwitterEdge, ..> as
    struct-of-arrays; SemanticGraph.h typedef).
    """

    structure: SpParMat
    attrs: dict

    @staticmethod
    def from_edges(
        grid: Grid, rows, cols, attrs: dict, nrows: int, ncols: int,
        capacity: int | None = None,
    ) -> "SemanticGraph":
        """Host construction: bucket edges + all attribute columns by owner
        tile (the SparseCommon shuffle carrying the payload struct)."""
        from .parallel.spmat import bucket_by_tile

        rows, cols, order, counts, starts, cap, lr, lc = bucket_by_tile(
            grid, rows, cols, nrows, ncols, capacity
        )
        attrs = {k: np.asarray(v)[order] for k, v in attrs.items()}
        pr_, pc_ = grid.pr, grid.pc
        R = np.full((pr_, pc_, cap), lr, np.int32)
        C = np.full((pr_, pc_, cap), lc, np.int32)
        V = np.zeros((pr_, pc_, cap), np.float32)
        A = {
            k: np.zeros((pr_, pc_, cap), v.dtype) for k, v in attrs.items()
        }
        for t in range(grid.size):
            i, j = divmod(t, pc_)
            s, e = starts[t], starts[t + 1]
            R[i, j, : e - s] = rows[s:e] - i * lr
            C[i, j, : e - s] = cols[s:e] - j * lc
            V[i, j, : e - s] = 1.0
            for k in attrs:
                A[k][i, j, : e - s] = attrs[k][s:e]
        sh = grid.tile_sharding()
        structure = SpParMat(
            rows=jax.device_put(jnp.asarray(R), sh),
            cols=jax.device_put(jnp.asarray(C), sh),
            vals=jax.device_put(jnp.asarray(V), sh),
            nnz=jax.device_put(
                jnp.asarray(counts.reshape(pr_, pc_), jnp.int32), sh
            ),
            nrows=int(nrows), ncols=int(ncols), grid=grid,
        )
        return SemanticGraph(
            structure=structure,
            attrs={k: jax.device_put(jnp.asarray(v), sh) for k, v in A.items()},
        )

    def materialize(self, pred) -> SpParMat:
        """Plain SpParMat of edges passing ``pred(attrs_dict) -> bool``.

        The reference's materialized filter (FilteredBFS.cpp's 'Materialize'
        branch). ``pred`` receives a dict of per-slot arrays.
        """
        return _filter_jit(self, pred, "materialize")

    def mask(self, pred) -> SpParMat:
        """Same structure, values = pred as 0/1 float — pair with
        ``FILTERED_SELECT2ND_MAX`` (or any value-aware semiring) for
        on-the-fly filtering without re-layout."""
        return _filter_jit(self, pred, "mask")


@partial(jax.jit, static_argnames=("pred", "mode"))
def _filter_jit(g: SemanticGraph, pred, mode: str) -> SpParMat:
    """Shared scaffold for both filter modes: mode="materialize" compacts
    passing entries, mode="mask" rewrites values to the 0/1 predicate."""
    S = g.structure
    names = tuple(sorted(g.attrs))

    def body(rows, cols, vals, nnz, *attr_arrays):
        t = S.local_tile(rows, cols, vals, nnz)
        attrs = {k: a[0, 0] for k, a in zip(names, attr_arrays)}
        ok = t.valid_mask() & pred(attrs)
        if mode == "materialize":
            out = t._select(ok)
        else:
            out = dataclasses.replace(t, vals=ok.astype(t.vals.dtype))
        return SpParMat._pack_tile(out)

    r, c, v, n = jax.shard_map(
        body,
        mesh=S.grid.mesh,
        in_specs=(TILE_SPEC,) * (4 + len(names)),
        out_specs=(TILE_SPEC,) * 4,
    )(S.rows, S.cols, S.vals, S.nnz, *(g.attrs[k] for k in names))
    return dataclasses.replace(S, rows=r, cols=c, vals=v, nnz=n)


def filtered_bfs(
    g: SemanticGraph, pred, source, *, materialize: bool = False
):
    """BFS over edges passing ``pred`` (≈ FilteredBFS.cpp).

    ``materialize=False`` runs the on-the-fly mode: one elementwise mask
    pass + the value-aware semiring; ``True`` compacts a filtered copy
    first (wins when many BFS runs share one filter).
    Returns (parents, levels, iterations).
    """
    from .models.bfs import bfs

    if materialize:
        return bfs(g.materialize(pred), source)
    return bfs(g.mask(pred), source, sr=FILTERED_SELECT2ND_MAX)


def filtered_mis(g: SemanticGraph, pred, key) -> tuple[DistVec, Array]:
    """Luby MIS on the filtered graph (≈ FilteredMIS.cpp). The filter is
    materialized because MIS iterates on the same structure."""
    from .models.mis import mis

    return mis(g.materialize(pred), key)
