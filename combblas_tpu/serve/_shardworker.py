"""Slice subprocess entry point (round 20, sharded serving).

``python -m combblas_tpu.serve._shardworker --fd N`` is what
``ProcSlice`` spawns: one OS process hosting ONE row slab of the
sharded graph (a ``shard.SliceRuntime``) with its OWN JAX runtime —
the parent pins ``JAX_PLATFORMS=cpu`` and a per-slice
``--xla_force_host_platform_device_count`` (1: a slice IS the host in
the multi-host story; the virtual mesh lives across processes, not
inside one) before exec.

Protocol: the ``_procworker`` conventions verbatim — framed request/
reply on the inherited socketpair (``{"id": n, "op": ...}`` →
``{"id": n, "ok": ...}``), unsolicited ``{"hb": {...}}`` heartbeats
carrying depth/frontier/serving so the router's ``ReplicaProc``
machinery distinguishes wedged from busy, and op dispatch shared with
the in-process slice through :func:`shard.dispatch_slice_op` — one
protocol, two transports.

Unlike ``_procworker``, graph payloads DO cross the socket at first
boot: the slab COO rides the frame codec's native ndarray channel
(``__ndb__`` hoisting) because no whole-graph checkpoint exists to
load from — sharding is the point.  Respawn boots recover from the
slice's own home directory (slab snapshot + per-slice WAL suffix)
and ship nothing.
"""

from __future__ import annotations

import argparse
import os
import socket
import sys
import threading
import time
import traceback

# Pin the runtime BEFORE jax is imported anywhere below; the parent
# exports these through env, the defaults cover hand-run workers.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "--xla_force_host_platform_device_count" not in os.environ.get(
    "XLA_FLAGS", ""
):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=1"
    )

# import-light; reads COMBBLAS_OBS (pinned by the parent) at import
from .. import obs  # noqa: E402


class ShardWorker:
    """The child-side dispatcher: one SliceRuntime, one channel."""

    def __init__(self, channel, hb_interval_s: float = 0.25,
                 metrics_interval_s: float = 1.0):
        self.ch = channel
        self.rt = None
        self.hb_interval_s = hb_interval_s
        self.metrics_interval_s = metrics_interval_s
        self._last_snap_t = 0.0
        self._hb_stop = threading.Event()
        self._busy = 0
        self._busy_lock = threading.Lock()

    def _reply(self, rid, result=None, exc: Exception | None = None):
        from .ipc import ChannelClosed

        try:
            if exc is None:
                self.ch.send({"id": rid, "ok": True, "result": result})
            else:
                self.ch.send({
                    "id": rid, "ok": False,
                    "etype": type(exc).__name__,
                    "error": str(exc),
                    "retry_after_s": getattr(exc, "retry_after_s",
                                             None),
                })
        except ChannelClosed:
            pass  # parent died; the recv loop exits on its own

    # -- heartbeat ---------------------------------------------------------

    def _hb_loop(self):
        from .ipc import ChannelClosed

        while not self._hb_stop.wait(self.hb_interval_s):
            rt = self.rt
            if rt is None:
                continue
            hb = {
                "t": time.time(),
                "pid": os.getpid(),
                "depth": self._busy,
                "serving": True,
                "slice": rt.idx,
                "wal_frontier": int(rt.version.wal_seq),
                "graph_version": int(rt.version.vid),
            }
            if obs.ENABLED:
                now = time.monotonic()
                if now - self._last_snap_t >= self.metrics_interval_s:
                    self._last_snap_t = now
                    try:
                        obs.count("serve.shard.hb_snapshots")
                        hb["metrics"] = obs.metrics_snapshot()
                    except Exception:
                        pass  # liveness outranks telemetry
            try:
                self.ch.send({"hb": hb})
            except ChannelClosed:
                return

    # -- boot --------------------------------------------------------------

    def _op_boot(self, m: dict) -> dict:
        from ..parallel.grid import Grid
        from .shard import SliceRuntime

        grid = Grid.make(1, 1)
        kinds = tuple(m["kinds"])
        common = dict(
            fsync=m.get("fsync"),
            max_iters=m.get("max_iters"),
            propagate_hops=int(m.get("propagate_hops", 2)),
            checkpoint_every=int(m.get("checkpoint_every", 0)),
            checkpoint_retain=int(m.get("checkpoint_retain", 2)),
        )
        if m.get("recover"):
            self.rt = SliceRuntime.recover(
                grid, int(m["idx"]), m["home"], kinds, **common
            )
        else:
            import numpy as np

            feats = m.get("features")
            self.rt = SliceRuntime.build(
                grid, int(m["idx"]), int(m["row0"]), int(m["row1"]),
                int(m["nrows"]), int(m["ncols"]),
                np.asarray(m["rows"]), np.asarray(m["cols"]),
                m.get("weights"), kinds,
                features=None, home=m.get("home"), **common,
            )
            if feats is not None:
                # the build path slices features by global row bounds;
                # the wire ships the PRE-SLICED slab — attach directly
                self.rt.attach_features(np.asarray(feats))
                if m.get("home"):
                    np.save(
                        os.path.join(m["home"], "features.npy"),
                        np.asarray(feats),
                    )
        warmed = {}
        if m.get("warmup", True):
            try:
                warmed = {
                    f"{k}/{w}": s
                    for (k, w), s in self.rt.warmup(
                        widths=m.get("warmup_widths")
                    ).items()
                }
            except Exception as e:
                warmed = {"error": repr(e)}
        self.hb_interval_s = float(
            m.get("hb_interval_s", self.hb_interval_s)
        )
        threading.Thread(
            target=self._hb_loop, name="combblas-shard-hb",
            daemon=True,
        ).start()
        return {
            "pid": os.getpid(),
            "slice": self.rt.idx,
            "rows": [self.rt.row0, self.rt.row1],
            "nnz": int(self.rt.version.nnz),
            "wal_seq": int(self.rt.version.wal_seq),
            "device_bytes": self.rt.device_bytes(),
            "warmed": warmed,
        }

    # -- dispatch ----------------------------------------------------------

    def dispatch(self, m: dict) -> bool:
        from .shard import dispatch_slice_op

        rid = m.get("id")
        op = m.get("op")
        try:
            if op == "boot":
                self._reply(rid, result=self._op_boot(m))
            elif op == "close":
                self._hb_stop.set()
                if self.rt is not None:
                    self.rt.close()
                self._reply(rid, result={"closed": True})
                return False
            else:
                with self._busy_lock:
                    self._busy += 1
                try:
                    self._reply(
                        rid, result=dispatch_slice_op(self.rt, op, m)
                    )
                finally:
                    with self._busy_lock:
                        self._busy -= 1
        except Exception as e:
            # a failed op fails ITS request, never the worker — the
            # router decides quarantine vs per-request handling
            if self.rt is not None:
                self.rt.worker_errors += 1
            self._reply(rid, exc=e)
        return True

    def run(self) -> None:
        import socket as _socket

        while True:
            try:
                m = self.ch.recv(timeout=1.0)
            except _socket.timeout:
                continue
            except Exception:
                break  # ChannelClosed / corrupt frame: parent gone
            if "hb" in m:
                continue
            if not self.dispatch(m):
                break
        self._hb_stop.set()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--fd", type=int, required=True,
                    help="inherited socketpair fd (pass_fds)")
    ap.add_argument("--hb-interval-s", type=float, default=0.25)
    args = ap.parse_args(argv)
    sock = socket.socket(fileno=args.fd)
    from .ipc import Channel

    worker = ShardWorker(
        Channel(sock, peer="parent"),
        hb_interval_s=args.hb_interval_s,
    )
    try:
        worker.run()
    except Exception:
        traceback.print_exc(file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
