"""Server — the worker loop tying engine + scheduler + batcher together.

``submit()`` returns a ``concurrent.futures.Future`` immediately; a
single background worker thread owns ALL device execution (one
execution stream, like one TPU), waking on submissions and flush
deadlines, popping ready batches, padding them into lane buckets, and
scattering lane results back to futures. ``submit_many`` is the bulk
front door; ``stats()`` surfaces queue depth, batch occupancy, plan
cache and trace counts without needing obs enabled.

The worker path is the resilience ladder (docs/serving.md
"Resilience"): expired requests are dropped before they occupy a lane,
a failed batch is bisected and retried under a bounded per-request
budget (one poison request fails alone, lane-mates survive),
top-level batch outcomes feed per-kind circuit breakers, the loop
backs off exponentially on scheduler-level errors, and
``swap_graph()`` atomically replaces the served graph version under
load with the plan cache surviving. ``health()`` is the pollable
liveness surface; ``Server.faults`` the deterministic fault-injection
hook every recovery path is tested through.

Usage::

    engine = GraphEngine.from_coo(grid, rows, cols, n)
    with engine.serve(ServeConfig(lane_widths=(1, 4, 16))) as srv:
        srv.warmup()                      # pre-trace every lane bucket
        f = srv.submit("bfs", root=7)
        print(f.result()["levels"][:10])
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
from collections import deque
from concurrent.futures import Future

from .. import obs
from ..obs.recorder import FlightRecorder
from . import batcher
from .faults import FaultInjector, InjectedFault
from .scheduler import BackpressureError, Scheduler, ServeConfig, _bump
from .slo import ErrorBudget


class Server:
    """In-process query server over one ``GraphEngine``."""

    def __init__(self, engine, config: ServeConfig | None = None,
                 tenant: str | None = None):
        self.engine = engine
        self.config = config or ServeConfig()
        #: Owning tenant (round 14, the multi-tenant pool): named in
        #: backpressure errors, threaded through the scheduler's and
        #: breakers' obs labels, and surfaced by stats()/health().
        #: ``None`` (single-tenant) keeps every label set unchanged.
        self.tenant = tenant
        self.scheduler = Scheduler(
            self.config, engine.nrows, engine.kinds(), tenant=tenant
        )
        # deterministic fault injection (serve/faults.py): unarmed by
        # default (one attribute read per check); chaos tests and the
        # chaos bench arm rules on this instance
        self.faults = FaultInjector()
        # -- production observability (round 15). The flight recorder
        # is ALWAYS ON by default (one ring append per batch, next to a
        # device launch; config.flight_recorder=False = one attribute
        # read); the SLO error budget exists only when a deadline SLO
        # is configured.  The scheduler shares the budget so rejection
        # and queue-sweep dispositions land in the same window.
        self._recorder = (
            FlightRecorder(
                capacity=self.config.flight_recorder_events,
                out_dir=self.config.flight_recorder_dir,
                min_interval_s=(
                    self.config.flight_recorder_min_interval_s
                ),
                tenant=tenant,
            )
            if self.config.flight_recorder else None
        )
        self.slo = (
            ErrorBudget(
                self.config.slo_target, self.config.slo_window_s,
                tenant=tenant,
            )
            if self.config.slo_deadline_s is not None else None
        )
        self.scheduler.slo = self.slo
        # scheduler-side bad records (rejections, queue sweeps) can be
        # the ones that burn through the budget — the breach dump must
        # fire no matter which side the crossing lands on
        self.scheduler.slo_breach = (
            lambda kind: self._flight_dump("slo_breach", query=kind)
        )
        self._scrape = None  # obs.export.ScrapeServer (serve_metrics)
        self._wake = threading.Condition()
        self._stop = False
        self._worker: threading.Thread | None = None
        self.batches = 0  # TOP-LEVEL batches (retries counted apart)
        self.retry_batches = 0  # bisection-recovery sub-batches
        self.completed = 0
        self.worker_errors = 0
        self.last_worker_error: Exception | None = None
        self.last_worker_error_at: float | None = None  # time.time()
        self._backoff_s = self.config.worker_backoff_s
        self._occupancy_sum = 0.0
        # per-kind execution-side disposition counters (queue-side
        # twins live on the scheduler); bumped only by the executing
        # thread, read by stats()
        self._timeout_exec: dict[str, int] = {}
        self._poisoned: dict[str, int] = {}
        self._retried: dict[str, int] = {}
        # -- write lane (docs/dynamic.md): the delta buffer, its
        # dedicated mutation thread, and the futures awaiting a merge.
        # _merge_mutex serializes whole merge cycles (drain -> apply ->
        # swap) so a pump_updates() call can never interleave with the
        # mutator and apply a batch against a stale parent version.
        self._upd_cond = threading.Condition()
        self._upd_buffer = None  # lazy dynamic.DeltaBuffer
        # (last_seq, Future, RequestTrace | None) per admitted batch
        self._upd_futs: deque = deque()
        self._upd_stop = False
        self._mutator: threading.Thread | None = None
        self._merge_mutex = threading.Lock()
        self.updates_submitted = 0
        self.update_merges = 0
        self.update_failures = 0
        self.updates_invalid = 0
        self._merge_modes: dict[str, int] = {}
        self._merge_s: dict[str, float] = {}
        # -- durability (round 16; docs/serving.md "Durability &
        # self-healing"): the write-ahead log every acknowledged
        # submit_update appends to BEFORE its future exists, and the
        # background checkpointer that snapshots the served version
        # (atomic tmp+rename, off the exec lock) and truncates the
        # replayed WAL prefix.  ``_wal is None`` (the default — no
        # ServeConfig.wal_dir / COMBBLAS_WAL) keeps every hot path at
        # one attribute read.
        self._wal = None
        self._wal_frontier = -1  # highest seq APPENDED (acknowledged)
        self._wal_applied = -1   # highest seq MERGED into the served
        #                          version (external hot-swap versions
        #                          are stamped here: pending appended
        #                          ops merge on top of them later)
        self._ckpt_dir: str | None = None
        self._ckpt_cond = threading.Condition()
        self._ckpt_lock = threading.Lock()  # one snapshot at a time
        self._ckpt_thread: threading.Thread | None = None
        self._ckpt_stop = False
        self._merges_since_ckpt = 0
        self.checkpoints = 0
        self.checkpoint_failures = 0
        self._attach_durability()

    # -- lifecycle ---------------------------------------------------------

    def warmup(self, kinds=None, widths=None) -> dict:
        """Warm every (kind, lane width) plan the configured buckets can
        produce — after this, steady-state serving never traces."""
        return self.engine.warmup(
            kinds=kinds,
            widths=tuple(widths or self.config.lane_widths),
        )

    def start(self) -> "Server":
        if self.scheduler.closed:
            # close() is final (admissions are refused forever); a
            # restarted worker could never receive work
            raise RuntimeError(
                "serve.Server is closed; build a new one via "
                "engine.serve()"
            )
        if self._worker is None or not self._worker.is_alive():
            self._stop = False
            self._worker = threading.Thread(
                target=self._loop, name="combblas-serve", daemon=True
            )
            self._worker.start()
        self._start_checkpointer()
        return self

    def close(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Close the front door (subsequent submits raise — a closed
        server must never strand a future) and stop the worker;
        ``drain=True`` executes everything still queued first (in the
        CALLER's thread, after the worker has joined — so it also
        drains a server whose worker was never started), else pending
        requests fail with a shutdown error."""
        self.scheduler.close()  # admissions refused from here on
        with self._wake:
            self._stop = True
            self._wake.notify_all()
        if self._worker is not None:
            self._worker.join(timeout)
            if self._worker.is_alive():
                # the engine has ONE execution thread; draining from
                # this thread while the worker still runs would race
                # it — surface the stuck worker instead
                raise TimeoutError(
                    f"serve worker did not stop within {timeout}s; "
                    "queue not drained (call close() again later)"
                )
            self._worker = None
        if drain:
            while self.scheduler.depth():
                self.pump(force=True)
        else:
            self.scheduler.fail_pending(
                RuntimeError("serve.Server closed without drain")
            )
        # the write lane stops LAST: its final merges may swap the
        # graph, and the read drain above must run on one consistent
        # execution stream either way (the engine lock serializes)
        self._stop_mutator(drain, timeout)
        # durability teardown (round 16): stop the checkpointer, take
        # one final snapshot when merges landed since the last (a
        # clean close leaves recovery with zero WAL to replay), and
        # release the log handle
        self._stop_checkpointer(timeout)
        if drain and self._ckpt_dir is not None:
            with self._ckpt_cond:
                dirty = self._merges_since_ckpt > 0
            if dirty:
                self.checkpoint_now(reason="close")
        if self._wal is not None:
            self._wal.close()
        if self._scrape is not None:
            from ..obs import export

            export.detach_scrape(self)

    def serve_metrics(self, port: int = 0, host: str = "127.0.0.1"
                      ) -> int:
        """Attach the live scrape surface (round 15): a stdlib-HTTP
        daemon thread serving ``/metrics`` (Prometheus text rendered
        from the obs registry), ``/healthz`` and ``/statz`` for this
        server.  ``port=0`` binds an ephemeral port; the bound port is
        returned.  Stopped by ``close()``."""
        from ..obs import export

        return export.attach_scrape(self, port=port, host=host)

    def __enter__(self) -> "Server":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # -- front door --------------------------------------------------------

    def submit(self, kind: str, root, timeout_s: float | None = None,
               trace_rid: int | str | None = None, trace=None) -> Future:
        """Admit one single-root query. Raises ``BackpressureError``
        when the bounded queue is full (reject + retry-after, never
        unbounded blocking); malformed roots come back as failed
        futures (error isolation — see scheduler.submit).
        ``trace_rid`` adopts an upstream trace-sampling decision
        (process-fleet stitching); ``trace`` adopts a live trace
        object (net-frontend stitching) — see scheduler.submit."""
        self.faults.check("scheduler.admit", kind=kind, root=root)
        fut = self.scheduler.submit(
            kind, root, timeout_s=timeout_s, trace_rid=trace_rid,
            trace=trace,
        )
        with self._wake:
            self._wake.notify_all()
        return fut

    def submit_many(self, kind: str, roots, timeout_s: float | None = None
                    ) -> list[Future]:
        """Bulk submit; stops at the first backpressure rejection and
        fails the REMAINING requests' futures with it (the caller sees
        exactly which prefix was admitted — one future per root, in
        order, generators included)."""
        roots = list(roots)  # single materialization: generator-safe
        out: list[Future] = []
        for i, r in enumerate(roots):
            try:
                self.faults.check("scheduler.admit", kind=kind, root=r)
                out.append(
                    self.scheduler.submit(kind, r, timeout_s=timeout_s)
                )
            except (BackpressureError, RuntimeError) as e:
                # backpressure, breaker fast-fail, a concurrent
                # close(), or an injected admission fault: either way
                # the caller must still get one future per root — the
                # admitted prefix's results stay reachable
                for _ in roots[i:]:
                    f = Future()
                    f.set_exception(e)
                    out.append(f)
                break
        with self._wake:
            self._wake.notify_all()
        return out

    # -- write lane (the mutation lane; docs/dynamic.md) -------------------

    def _make_update_buffer(self):
        from ..dynamic import DeltaBuffer

        return DeltaBuffer(
            capacity=self.config.update_buffer,
            nrows=self.engine.nrows,
            ncols=int(self.engine.version.ncols),
            retry_after_s=self.config.update_max_delay_s,
            # a durable server continues the WAL's seqno lineage —
            # replay dedup and snapshot stamps need ONE monotone
            # sequence line across process lives (round 16; the
            # frontier also covers non-durable merges made before an
            # attach_durability)
            start_seq=(
                self._wal_frontier + 1
                if (self._wal is not None
                    or getattr(self.engine, "owns_durability", False))
                else 0
            ),
        )

    # -- durability: WAL + background checkpointer (round 16) --------------

    def attach_durability(self, dirpath: str) -> None:
        """Attach the WAL + checkpointer to a RUNNING server — the
        fleet's home-promotion path (round 16): the promoted replica
        was built without durability (only the home owns the log) and
        takes it over at the frontier.  Idempotent for the same dir;
        a different dir raises (one log, one lineage)."""
        import os

        # the WHOLE attach runs under the write-admission lock: a
        # submit_update racing the attach would otherwise re-create
        # the buffer at seq 0 and acknowledge a write with no WAL
        # record in the window between the depth check and the log
        # opening (TOCTOU)
        with self._upd_cond:
            if self._wal is not None:
                if self._ckpt_dir == os.path.abspath(dirpath):
                    return
                raise RuntimeError(
                    f"server already durable at {self._ckpt_dir!r}; "
                    f"refusing to switch to {dirpath!r}"
                )
            if (
                self._upd_buffer is not None
                and self._upd_buffer.depth()
            ) or self._upd_futs:
                # pre-attach buffered ops (and drained batches whose
                # merge is still in flight — _merge_once runs outside
                # this lock) carry non-lineage seqs: they would
                # collide with the WAL's frontier numbering
                raise RuntimeError(
                    "cannot attach durability with un-merged buffered "
                    "writes pending; drain them first"
                )
            self._upd_buffer = None  # recreate at the WAL frontier
            self._attach_durability(dirpath)
        if self._worker is not None and self._worker.is_alive():
            self._start_checkpointer()

    def _attach_durability(self, d: str | None = None) -> None:
        """Attach the write-ahead log + checkpoint directory when
        configured (``ServeConfig.wal_dir`` > ``COMBBLAS_WAL`` > off).
        A server that was NOT booted from recovery writes a bootstrap
        snapshot at the current WAL frontier — recovery is always
        "latest snapshot + WAL suffix", so a base snapshot must exist
        before the first write is acknowledged."""
        import os

        from ..tuner import config as tuner_config

        if d is None:
            d = tuner_config.wal_dir(self.config.wal_dir)
        else:
            d = os.path.abspath(d)  # idempotence compares abspaths
        if getattr(self.engine, "owns_durability", False):
            # engine-owned durability (round 20, the sharded engine):
            # writes are logged PER-SLICE inside the engine's own
            # two-phase protocol — a server-level scalar WAL stacked
            # on top would double-log every write on a second lineage
            # and re-apply it at recovery.  The seqno frontier still
            # seeds from the engine's (vector-min) stamp so the delta
            # buffer continues the shared sequence line.
            if d is not None:
                raise ValueError(
                    f"wal_dir {d!r} configured, but the engine owns "
                    "its own durability (per-slice WALs); remove "
                    "wal_dir / COMBBLAS_WAL for sharded serving"
                )
            self._wal_frontier = int(self.engine.version.wal_seq)
            self._wal_applied = self._wal_frontier
            return
        if d is None:
            return
        if self.engine.version.host_coo is None:
            raise ValueError(
                "durability (wal_dir) needs the host edge list: build "
                "the engine with GraphEngine.from_coo(keep_coo=True) "
                "or boot via Server.from_recovery"
            )
        from ..dynamic import wal as dyn_wal
        from ..utils import checkpoint as ckpt

        os.makedirs(d, exist_ok=True)
        v = self.engine.version
        wal = dyn_wal.open_wal(d, fsync=self.config.wal_fsync)
        if getattr(v, "recovered_from", None) is None:
            # boot-from-COO: the bootstrap snapshot below would
            # truncate the WAL at the new frontier — REFUSE if that
            # would destroy acknowledged writes no snapshot holds
            # ("no acknowledged write is lost" is the whole contract)
            snaps = ckpt.list_snapshots(d)
            covered = ckpt.snapshot_seq(snaps[-1]) if snaps else -1
            unreplayed = wal.replay(after_seq=covered)
            if unreplayed:
                wal.close()
                raise RuntimeError(
                    f"durability dir {d!r} holds "
                    f"{sum(len(b) for b in unreplayed)} acknowledged "
                    "write op(s) no snapshot covers; booting from a "
                    "fresh COO here would silently destroy them — "
                    "recover them (Server.from_recovery / "
                    "FleetRouter.from_recovery) or point wal_dir at "
                    "a fresh directory"
                )
        self._ckpt_dir = d
        self._wal = wal
        # the seqno frontier is the max over BOTH the log's position
        # and the version's own stamp: a server that merged writes
        # non-durably before attach_durability() must not restart
        # sequence numbers below its snapshot stamp (later snapshots
        # would sort before the bootstrap one and recovery would skip
        # every post-attach record)
        self._wal_frontier = max(self._wal.position(), int(v.wal_seq))
        if v.wal_seq < self._wal_frontier:
            # boot over an exhausted (fully snapshotted/replayed) log:
            # this version DEFINES a fresh lineage at the frontier
            v.wal_seq = self._wal_frontier
        self._wal_applied = v.wal_seq
        snaps = ckpt.list_snapshots(d)
        covered = ckpt.snapshot_seq(snaps[-1]) if snaps else None
        if covered is None or covered < v.wal_seq or (
            getattr(v, "recovered_from", None) is None
        ):
            # the attached state must be recoverable NOW as "snapshot
            # + suffix": fresh-COO boots always snapshot (they define
            # the lineage), and a recovered version snapshots exactly
            # when its replayed suffix outruns the newest snapshot
            # (compacting the WAL as a side effect).  A bootstrap
            # failure raises: durability was promised.
            self.checkpoint_now(reason="bootstrap", _raise=True)

    @property
    def durable(self) -> bool:
        return self._wal is not None or getattr(
            self.engine, "owns_durability", False
        )

    def checkpoint_now(self, reason: str = "manual",
                       _raise: bool = False) -> dict | None:
        """Snapshot the CURRENT served version (atomic tmp+rename,
        off the execution lock — versions are immutable, so reading
        one concurrently with serving is safe), truncate the WAL
        prefix the snapshot now covers, and prune snapshots beyond the
        retention depth.  Returns ``{"path", "wal_seq", "reason"}`` or
        ``None`` (disabled / failed — a failed auto-checkpoint leaves
        the previous snapshot and the un-truncated WAL intact and
        retries on the next trigger)."""
        import os

        if self._ckpt_dir is None:
            if getattr(self.engine, "owns_durability", False):
                # delegate: the sharded engine snapshots every slice
                # at its own frontier and re-writes the manifest
                try:
                    return self.engine.checkpoint_now(reason=reason)
                except Exception:
                    self.checkpoint_failures += 1
                    if _raise:
                        raise
                    return None
            return None
        from ..tuner import config as tuner_config
        from ..utils import checkpoint as ckpt

        v = self.engine.version
        with self._ckpt_lock:
            try:
                self.faults.check(
                    "checkpoint.save", seq=v.wal_seq, reason=reason
                )
                path = os.path.join(
                    self._ckpt_dir, ckpt.snapshot_name(v.wal_seq)
                )
                ckpt.save_version(path, v)
                with self._ckpt_cond:
                    self._merges_since_ckpt = 0
                self.checkpoints += 1
                obs.count("serve.checkpoint.auto", reason=reason)
                retain = tuner_config.checkpoint_retain(
                    self.config.checkpoint_retain
                )
                for old in ckpt.list_snapshots(self._ckpt_dir)[:-retain]:
                    try:
                        os.unlink(old)
                    except OSError:
                        pass  # racing pruner / readonly: retried next
                if self._wal is not None:
                    # truncate only through the OLDEST retained
                    # snapshot: the corrupt-newest fallback
                    # (checkpoint_retain's whole purpose) needs the
                    # WAL to still cover the predecessor→newest gap,
                    # or falling back would silently lose that span
                    snaps = ckpt.list_snapshots(self._ckpt_dir)
                    self._wal.truncate(
                        ckpt.snapshot_seq(snaps[0]) if snaps
                        else v.wal_seq
                    )
                return {
                    "path": path, "wal_seq": int(v.wal_seq),
                    "reason": reason,
                }
            except Exception as e:
                self.checkpoint_failures += 1
                obs.count(
                    "serve.checkpoint.failed",
                    exc_type=type(e).__name__,
                )
                self._flight_dump("checkpoint_failed", error=repr(e))
                if _raise:
                    raise
                return None

    def _ckpt_note_merge(self) -> None:
        if self._ckpt_dir is None:
            return
        with self._ckpt_cond:
            self._merges_since_ckpt += 1
            self._ckpt_cond.notify_all()

    def _start_checkpointer(self) -> None:
        if self._ckpt_dir is None:
            return
        if self._ckpt_thread is None or not self._ckpt_thread.is_alive():
            self._ckpt_stop = False
            self._ckpt_thread = threading.Thread(
                target=self._ckpt_loop, name="combblas-serve-ckpt",
                daemon=True,
            )
            self._ckpt_thread.start()

    def _ckpt_loop(self) -> None:
        from ..tuner import config as tuner_config

        every = tuner_config.checkpoint_every(
            self.config.checkpoint_every
        )
        interval = self.config.checkpoint_interval_s
        last_t = time.monotonic()
        backoff = self.config.worker_backoff_s
        while True:
            with self._ckpt_cond:
                while not self._ckpt_stop:
                    now = time.monotonic()
                    if self._merges_since_ckpt >= every or (
                        interval is not None
                        and self._merges_since_ckpt > 0
                        and now - last_t >= interval
                    ):
                        break
                    if interval is None or self._merges_since_ckpt == 0:
                        # nothing to snapshot until a merge lands —
                        # block until _ckpt_note_merge (or stop)
                        # notifies, never poll an idle server
                        self._ckpt_cond.wait()
                    else:
                        self._ckpt_cond.wait(
                            max(0.005, interval - (now - last_t))
                        )
                if self._ckpt_stop:
                    break  # the final snapshot is close()'s call
            ok = self.checkpoint_now(reason="auto") is not None
            last_t = time.monotonic()
            if ok:
                backoff = self.config.worker_backoff_s
            else:
                # a failed snapshot leaves _merges_since_ckpt set, so
                # the wait loop would re-trigger IMMEDIATELY: back off
                # (capped exponential, stop-notify still wakes us)
                # instead of re-serializing the version in a tight
                # loop against a broken disk
                with self._ckpt_cond:
                    if not self._ckpt_stop:
                        self._ckpt_cond.wait(backoff)
                backoff = min(2 * backoff,
                              self.config.worker_backoff_max_s)

    def _stop_checkpointer(self, timeout: float) -> None:
        if self._ckpt_thread is None:
            return
        with self._ckpt_cond:
            self._ckpt_stop = True
            self._ckpt_cond.notify_all()
        self._ckpt_thread.join(timeout)
        if self._ckpt_thread.is_alive():
            raise TimeoutError(
                f"serve checkpointer did not stop within {timeout}s"
            )
        self._ckpt_thread = None

    @staticmethod
    def from_recovery(grid, config: ServeConfig | None = None, *,
                      kinds=None, tenant: str | None = None,
                      combine: str | None = None) -> "Server":
        """Boot a server from crash recovery: latest valid snapshot in
        the durability dir + WAL-suffix replay
        (``dynamic.wal.recover_version`` — bit-exact with the engine
        that crashed, acknowledged writes included), with the WAL
        re-attached at the seqno frontier so the write lane resumes
        the same lineage.  Run ``warmup()`` before serving — with the
        shared plan store populated it replays the fleet's remembered
        lanes: zero retraces, zero re-measurement."""
        from ..dynamic import wal as dyn_wal
        from ..tuner import config as tuner_config
        from .engine import GraphEngine

        cfg = config or ServeConfig()
        d = tuner_config.wal_dir(cfg.wal_dir)
        if d is None:
            raise ValueError(
                "from_recovery needs a durability dir "
                "(ServeConfig.wal_dir or COMBBLAS_WAL)"
            )
        # the Server attaches its own log handle afterwards
        version = dyn_wal.recover(
            d, grid, kinds=kinds, combine=combine, fsync=cfg.wal_fsync
        )
        engine = GraphEngine(grid, version=version, kinds=kinds)
        return Server(engine, cfg, tenant=tenant)

    def submit_update(self, ops) -> Future:
        """Admit a batch of edge mutations — ``ops`` is a sequence of
        ``("insert" | "delete" | "upsert", row, col[, weight])`` tuples
        admitted ATOMICALLY into the bounded delta buffer.  Returns a
        Future that resolves (``{"version", "nnz", "mode", "ops",
        "merge_s"}``) once the merge CONTAINING these ops has been
        applied and atomically swapped in; reads submitted after that
        point see the mutated graph.

        Mirrors the read lane's contracts: a full buffer raises
        ``BackpressureError`` (reject + retry-after, never unbounded
        buffering), malformed ops come back as failed futures (error
        isolation — lane-mates in the same call are rejected with
        them, since admission is atomic), and a closed server raises.
        Writes COALESCE: the merge runs off the execution lock on the
        mutation thread while reads keep executing; only the version
        swap itself takes the lock."""
        from ..dynamic import DeltaOverflowError

        if self.scheduler.closed:
            raise RuntimeError(
                "serve.Server is closed; no further admissions"
            )
        if not getattr(
            self.engine, "supports_updates",
            self.engine.version.host_coo is not None,
        ):
            raise ValueError(
                "the mutation lane needs the host edge list: build "
                "the engine with GraphEngine.from_coo(keep_coo=True)"
            )
        ops = list(ops)
        self.faults.check("update.submit", nops=len(ops))
        fut: Future = Future()
        with self._upd_cond:
            if self.scheduler.closed or self._upd_stop:
                # RE-checked under the lock: a quarantine racing the
                # unlocked check above has already failed/cleared
                # _upd_futs — admitting here would append a future
                # nothing will ever settle
                raise RuntimeError(
                    "serve.Server is closed; no further admissions"
                )
            if self._upd_buffer is None:
                self._upd_buffer = self._make_update_buffer()
            try:
                last = self._upd_buffer.add_many(ops)
            except DeltaOverflowError as e:
                raise BackpressureError(
                    self._upd_buffer.depth(), e.retry_after_s,
                    tenant=self.tenant,
                ) from e
            except ValueError as e:
                # malformed op: fail THIS future, poison nothing
                self.updates_invalid += 1
                obs.count("serve.update.invalid")
                fut.set_exception(e)
                return fut
            if self._wal is not None:
                # durability: the record hits disk BEFORE the caller
                # holds a future — "acknowledged" and "durable" are
                # the same event.  A failed append REJECTS the write
                # (tail rollback un-admits the ops; nothing else
                # could touch the buffer: every mutator holds
                # _upd_cond): the caller retries, and a write that
                # was never acknowledged was never promised.
                from ..dynamic.delta import _OP_CODE

                first = last - len(ops) + 1
                try:
                    self.faults.check("wal.append", nops=len(ops))
                    self._wal.append(
                        first,
                        [o[1] for o in ops],
                        [o[2] for o in ops],
                        [o[3] if len(o) > 3 else 1.0 for o in ops],
                        [_OP_CODE[o[0]] for o in ops],
                    )
                    self._wal_frontier = last
                except Exception as e:
                    self._upd_buffer.rollback(first)
                    obs.count("serve.wal.append_failed")
                    try:
                        # the line may have reached disk before the
                        # failure (fsync raised): tombstone the range
                        # so a crash cannot resurrect a write this
                        # caller is being told FAILED.  Positional —
                        # a later retry reusing the seqs is
                        # untouched.  Best-effort: if even this write
                        # fails, recovery may conservatively re-apply
                        # the range.
                        self._wal.append_drop(first, last)
                    except Exception:
                        pass
                    self._flight_dump("wal_append_failed",
                                      error=repr(e))
                    raise RuntimeError(
                        f"write NOT acknowledged: WAL append failed "
                        f"({e!r}); retry"
                    ) from e
            # write-lane trace (round 15): buffer wait -> merge ->
            # [fanout ->] swap -> settle; rid keyed by the batch's last
            # sequence number, so sampling is deterministic per op set
            tr = obs.update_trace(f"upd-{last}", tenant=self.tenant)
            if tr is not None:
                # the fleet's fan-out callback marks its stage through
                # this handle (it only ever sees the future)
                fut._combblas_trace = tr
            self._upd_futs.append((last, fut, tr))
            self.updates_submitted += 1
            obs.count("serve.update.submitted")
            if self.config.update_autostart:
                self._ensure_mutator()
            self._upd_cond.notify_all()
        return fut

    def _ensure_mutator(self) -> None:
        # called under _upd_cond
        if self._mutator is None or not self._mutator.is_alive():
            self._upd_stop = False
            self._mutator = threading.Thread(
                target=self._mutate_loop, name="combblas-serve-mutate",
                daemon=True,
            )
            self._mutator.start()

    def _updates_due(self, now: float) -> bool:
        b = self._upd_buffer
        if b is None:
            return False
        d = b.depth()
        if d == 0:
            return False
        if d >= self.config.update_flush:
            return True
        age = b.oldest_age(now)
        return age is not None and age >= self.config.update_max_delay_s

    def pump_updates(self, force: bool = False) -> int:
        """One synchronous write-lane step (the mutation thread's body,
        callable directly for deterministic tests / worker-less
        embedding): merge + swap the pending delta batch if one is due
        (or unconditionally under ``force``).  Returns ops merged."""
        if not force and not self._updates_due(time.monotonic()):
            return 0
        return self._merge_once()

    def _merge_once(self) -> int:
        """Drain -> apply_delta -> swap -> settle one batch's futures.
        Serialized on ``_merge_mutex`` so concurrent callers can never
        apply a batch against a stale parent version (which would
        silently drop the other batch's mutations)."""
        with self._merge_mutex:
            with self._upd_cond:
                b = self._upd_buffer
                batch = b.drain() if b is not None else None
                futs = []
                if batch is not None:
                    while (
                        self._upd_futs
                        and self._upd_futs[0][0] <= batch.last_seq
                    ):
                        _seq, f, tr = self._upd_futs.popleft()
                        futs.append((f, tr))
            if batch is None:
                return 0
            traces = [tr for _f, tr in futs if tr is not None]
            t_drain = time.perf_counter()
            for tr in traces:
                tr.mark("buffer_wait", now=t_drain)
            rec = self._recorder
            try:
                self.faults.check("update.merge", nops=len(batch))
                version = self.engine.apply_delta(batch)
                # the version now contains every op through this seq:
                # snapshot meta stamps it, recovery replays past it
                version.wal_seq = batch.last_seq
                t_merge = time.perf_counter()
                for tr in traces:
                    tr.mark("merge", now=t_merge)
                res = self.swap_graph(version)
                self._wal_applied = batch.last_seq
                t_swap = time.perf_counter()
                st = version.dyn.last_stats
                for tr in traces:
                    tr.mark("swap", now=t_swap)
                    tr.annotate(
                        mode=st.mode, ops=len(batch),
                        version=res["version"],
                    )
                self.update_merges += 1
                self._merge_modes[st.mode] = (
                    self._merge_modes.get(st.mode, 0) + 1
                )
                self._merge_s[st.mode] = (
                    self._merge_s.get(st.mode, 0.0) + st.latency_s
                )
                obs.count("serve.update.merges", mode=st.mode)
                obs.observe("serve.update.coalesced", len(batch))
                if rec is not None:
                    rec.record(
                        "serve.merge", ops=len(batch), mode=st.mode,
                        outcome="ok", version=res["version"],
                        merge_s=round(t_merge - t_drain, 6),
                        swap_s=round(t_swap - t_merge, 6),
                    )
                payload = {
                    "version": res["version"],
                    "nnz": res["nnz"],
                    "mode": st.mode,
                    "ops": len(batch),
                    "merge_s": st.latency_s,
                }
                # settle BEFORE finishing the traces: done-callbacks
                # run synchronously inside settle, and the fleet's
                # fan-out callback marks its stage through the trace
                # handle stashed on the future — finishing afterwards
                # lets that mark land inside the committed record
                for f, _tr in futs:
                    batcher.settle(f, result=payload)
                for tr in traces:
                    tr.finish(status="ok", stage="settle")
                self._ckpt_note_merge()  # checkpoint trigger (rnd 16)
            except Exception as e:  # failure touches THIS batch only:
                # the old version keeps serving, later merges proceed
                self.update_failures += 1
                obs.count(
                    "serve.update.failed", exc_type=type(e).__name__
                )
                if self._wal is not None:
                    # the live lineage REJECTED these ops (their
                    # futures fail below): tombstone the range so a
                    # crash-recovery replay cannot resurrect writes
                    # the callers were told failed.  Best-effort — if
                    # even the tombstone cannot be written, recovery
                    # may re-apply the range (the conservative side).
                    try:
                        self._wal.append_drop(
                            batch.first_seq, batch.last_seq
                        )
                        self._wal_applied = batch.last_seq
                    except Exception:
                        obs.count("serve.wal.append_failed")
                if rec is not None:
                    rec.record(
                        "serve.merge", ops=len(batch),
                        outcome="error", error=repr(e),
                    )
                self._flight_dump(
                    "merge_failed", ops=len(batch), error=repr(e)
                )
                for f, _tr in futs:
                    batcher.settle(f, exc=e)
                for tr in traces:
                    tr.finish(status="error", stage="settle")
            return len(batch)

    def _mutate_loop(self) -> None:
        while True:
            with self._upd_cond:
                while not self._upd_stop and not self._updates_due(
                    time.monotonic()
                ):
                    b = self._upd_buffer
                    age = b.oldest_age() if b is not None else None
                    self._upd_cond.wait(
                        None if age is None else max(
                            0.001,
                            self.config.update_max_delay_s - age,
                        )
                    )
                if self._upd_stop and (
                    self._upd_buffer is None
                    or self._upd_buffer.depth() == 0
                ):
                    break
            # stopping with pending ops falls through: the final
            # merge(s) run before the thread exits (close() drains)
            self._merge_once()

    def _stop_mutator(self, drain: bool, timeout: float,
                      abort_exc: Exception | None = None) -> None:
        futs: list = []
        with self._upd_cond:
            self._upd_stop = True
            if not drain:
                # abort BEFORE waking the mutator: its stop path merges
                # whatever is still buffered, and a no-drain close must
                # abandon those writes (matching the read lane), not
                # apply-and-swap them behind the caller's back.  An
                # IN-FLIGHT merge already popped its futures, so what
                # remains here maps exactly to the drained-away ops.
                b = self._upd_buffer
                if b is not None:
                    b.drain()
                futs = [(f, t) for _s, f, t in self._upd_futs]
                self._upd_futs.clear()
            self._upd_cond.notify_all()
        if not drain:
            exc = abort_exc if abort_exc is not None else RuntimeError(
                "serve.Server closed without drain"
            )
            for f, tr in futs:
                batcher.settle(f, exc=exc)
                if tr is not None:  # abandoned writes still close
                    # their sampled trace (status tells the story)
                    tr.finish(status="aborted", stage="settle")
        if self._mutator is not None:
            self._mutator.join(timeout)
            if self._mutator.is_alive():
                raise TimeoutError(
                    f"serve mutation thread did not stop within "
                    f"{timeout}s"
                )
            self._mutator = None
        # a never-started mutator (update_autostart=False) may still
        # hold pending ops on a draining close: merge them here
        if drain and (
            self._upd_buffer is not None and self._upd_buffer.depth()
        ):
            while self._merge_once():
                pass

    # -- worker ------------------------------------------------------------

    def _flight_dump(self, reason: str, **extra):
        """Snapshot the flight-recorder ring (no-op when disabled;
        rate-limited inside the recorder)."""
        rec = self._recorder
        if rec is None:
            return None
        return rec.dump(reason, **extra)

    def _slo_bad(self, kind: str) -> None:
        """One bad SLO disposition; a budget-burn crossing dumps the
        flight recorder (the post-mortem is cheapest NOW, while the
        ring still holds the window that burned the budget)."""
        if self.slo is not None and self.slo.record(False, kind=kind):
            self._flight_dump("slo_breach", query=kind)

    def _slo_ok(self, req) -> None:
        if self.slo is not None:
            self.slo.record(True, kind=req.kind)

    def _on_exec_timeout(self, req) -> None:
        _bump(self._timeout_exec, req.kind)
        self._slo_bad(req.kind)

    def _on_lane_error(self, req) -> None:
        self._slo_bad(req.kind)

    def _drop_dead(self, reqs, now: float | None = None) -> list:
        """Deadline enforcement at EXECUTION time: a request that is
        already settled (client cancel) or already past its deadline is
        dropped here, before it occupies a device lane — the queue
        sweep in ``pop_ready`` catches most, but a request can expire
        between pop and execute (or during a failing batch's bisection
        retries). Returns the live remainder."""
        now = time.monotonic() if now is None else now
        live = []
        for r in reqs:
            if r.future.done():
                continue
            if r.expired(now):
                batcher.expire(
                    r, "expired before execution", self._on_exec_timeout
                )
            else:
                live.append(r)
        return live

    def _run_batch(self, reqs, *, toplevel: bool = True) -> None:
        """Execute one batch with the full recovery ladder: drop dead
        requests, run, and on failure hand the survivors to the
        bisection retrier. Top-level outcomes (not bisection
        sub-batches) feed the kind's circuit breaker, so one poisoned
        request cannot open it.

        Observability (round 15): sampled requests' traces MARK each
        stage transition here (queue wait / retry wait -> assemble ->
        execute -> scatter; the marks telescope to the e2e latency),
        and the always-on flight recorder takes one per-batch event
        with the same stage decomposition — per batch, not per
        request, so it can afford to run unconditionally."""
        live = self._drop_dead(reqs)
        if not live:
            return
        kind = live[0].kind
        breaker = self.scheduler.breakers.get(kind)
        rec = self._recorder
        t_pop = time.perf_counter()
        # oldest request's wait at pop time (monotonic base, matching
        # Request.submitted_at) — the recorder's queue-wait fact
        wait_s = time.monotonic() - live[0].submitted_at
        # the wait a request pays BEFORE the worker picks it up:
        # queue/flush wait at top level (in a pool, this includes the
        # WFQ credit wait — one number, by design), sibling-bisection
        # wait on retry sub-batches
        stage0 = "queue_wait" if toplevel else "retry_wait"
        for r in live:
            if r.trace is not None:
                r.trace.mark(stage0, now=t_pop)
        t_asm = t_exec = None
        try:
            self.faults.check("batch.assemble", kind=kind,
                              width=len(live))
            sources = batcher.assemble(
                live, self.config.lane_widths, record=toplevel
            )
            if toplevel:
                # occupancy/batch accounting measures COALESCING, so
                # retry sub-batches stay out of it (they are visible
                # as retry_batches / per_kind retried instead)
                self.batches += 1
                self._occupancy_sum += len(live) / len(sources)
            else:
                self.retry_batches += 1
            t_asm = time.perf_counter()
            for r in live:
                if r.trace is not None:
                    r.trace.mark("assemble", now=t_asm)
            self.faults.check(
                "engine.execute", kind=kind,
                roots=tuple(r.root for r in live),
            )
            pm = self.engine.plan_misses
            result = self.engine.execute(kind, sources)
            t_exec = time.perf_counter()
            plan_src = "cold" if self.engine.plan_misses > pm else "warm"
            for r in live:
                if r.trace is not None:
                    r.trace.mark("execute", now=t_exec)
                    r.trace.annotate(
                        width=len(sources), plan=plan_src,
                        version=self.engine.version_id,
                    )
            self.faults.check("batch.scatter", kind=kind)
            self.completed += batcher.scatter(
                live, result,
                on_timeout=self._on_exec_timeout,
                on_ok=self._slo_ok if self.slo is not None else None,
                on_error=(
                    self._on_lane_error
                    if self.slo is not None else None
                ),
            )
            if rec is not None:
                now = time.perf_counter()
                rec.record(
                    "serve.batch", query=kind, width=len(sources),
                    requests=len(live), toplevel=toplevel,
                    outcome="ok", plan=plan_src,
                    version=self.engine.version_id,
                    queue_wait_s=round(wait_s, 6),
                    assemble_s=round(t_asm - t_pop, 6),
                    execute_s=round(t_exec - t_asm, 6),
                    scatter_s=round(now - t_exec, 6),
                    rids=[r.rid for r in live],
                )
            if breaker is not None and toplevel:
                breaker.record_success(time.monotonic(), kind)
        except Exception as e:  # failure touches THIS batch only
            now = time.perf_counter()
            for r in live:
                if r.trace is not None:
                    # however far the batch got, the elapsed time was
                    # execution-side work: charge it there so retry
                    # marks stay telescoping
                    r.trace.mark("execute", now=now)
            if rec is not None:
                rec.record(
                    "serve.batch", query=kind, requests=len(live),
                    toplevel=toplevel, outcome="error",
                    error=repr(e),
                    elapsed_s=round(now - t_pop, 6),
                    rids=[r.rid for r in live],
                )
            if breaker is not None and toplevel:
                if breaker.record_failure(time.monotonic(), kind):
                    self._flight_dump(
                        "breaker_open", query=kind, error=repr(e)
                    )
            self._recover(live, e)

    def _recover(self, reqs, exc: Exception) -> None:
        """Poisoned-batch isolation: a failed batch is bisected and
        retried so one poison request fails ALONE instead of taking
        its lane-mates with it. Each request rides at most
        ``retry_budget`` failing executions (budget 5 = a full
        16→8→4→2→1 bisection), then its future fails with the last
        error — bounded work, no stranded futures."""
        kind = reqs[0].kind
        budget = self.config.retry_budget
        retry = []
        poisoned = []
        for r in reqs:
            r.attempts += 1
            if r.attempts >= budget:
                if batcher.settle(r.future, exc=exc):
                    _bump(self._poisoned, kind)
                    obs.count("serve.requests", kind=kind,
                              status="error")
                    obs.count("serve.poison.isolated", kind=kind)
                    if r.trace is not None:
                        r.trace.finish(status="poisoned",
                                       stage="settle")
                    poisoned.append(r.rid)
            else:
                retry.append(r)
        if poisoned:
            # the poisoned batch's stage events are still in the ring:
            # snapshot NOW so the post-mortem holds them (one dump per
            # recover call, rate-limited inside the recorder) — and
            # BEFORE the SLO accounting, whose own breach dump would
            # otherwise rate-limit this one away
            self._flight_dump(
                "poisoned", query=kind, rids=poisoned, error=repr(exc)
            )
            for _rid in poisoned:
                self._slo_bad(kind)
        if not retry:
            return
        _bump(self._retried, kind, len(retry))
        obs.count("serve.retry.requests", len(retry), kind=kind)
        if len(retry) == 1:
            self._run_batch(retry, toplevel=False)
            return
        mid = (len(retry) + 1) // 2
        self._run_batch(retry[:mid], toplevel=False)
        self._run_batch(retry[mid:], toplevel=False)

    def _execute_batches(self, ready) -> None:
        for reqs in ready:
            # whole-batch guard: these requests are already popped, so
            # ANY failure (assemble, engine, scatter) must settle their
            # futures (possibly after bisection retries) — a stranded
            # future blocks its caller forever
            self._run_batch(reqs)

    def pump(self, force: bool = False) -> int:
        """One synchronous scheduling step (the worker's body, callable
        directly for deterministic tests / worker-less embedding):
        execute every batch currently due. Returns batches executed."""
        ready = self.scheduler.pop_ready(force=force)
        self._execute_batches(ready)
        return len(ready)

    def _loop(self) -> None:
        while True:
            with self._wake:
                if self._stop:
                    break
            # replica.death (round 16): OUTSIDE the recovery ladder by
            # design — when this fires the worker thread DIES, exactly
            # the failure mode the fleet supervisor exists to detect
            # (health() flips "down"; chaos tests and the recovery
            # bench kill replicas through this point).  The thread
            # exits without settling anything — a crash settles
            # nothing either.
            try:
                self.faults.check("replica.death")
            except InjectedFault:
                return
            # pump BEFORE sleeping: requests that arrived while the
            # previous batch executed (their notify found no waiter)
            # may already fill a lane bucket — flush-on-full must not
            # wait out the deadline
            try:
                pumped = self.pump()
                if self._backoff_s != self.config.worker_backoff_s:
                    # reset on success — and bring the gauge back down
                    # with it (a one-time write: steady state is free)
                    self._backoff_s = self.config.worker_backoff_s
                    obs.gauge("serve.worker.backoff_s", self._backoff_s)
                if pumped:
                    continue
            except Exception as e:  # the worker must outlive any one
                # pump: a dead worker with an open front door would
                # admit requests whose futures never complete. The
                # error is RETAINED and printed — an obs counter alone
                # would vanish with telemetry off (the default). Batch
                # failures never reach here (the recovery ladder
                # settles them); this is the scheduler-bug backstop,
                # so it backs off exponentially (capped, reset on
                # success) instead of spinning at a fixed 50 ms
                self.worker_errors += 1
                self.last_worker_error = e
                self.last_worker_error_at = time.time()
                obs.count(
                    "serve.worker.errors", exc_type=type(e).__name__
                )
                obs.gauge("serve.worker.backoff_s", self._backoff_s)
                self._flight_dump("worker_error", error=repr(e))
                traceback.print_exc(file=sys.stderr)
                time.sleep(self._backoff_s)
                self._backoff_s = min(
                    2 * self._backoff_s, self.config.worker_backoff_max_s
                )
                continue
            with self._wake:
                if self._stop:
                    break
                if self.scheduler.has_ready():
                    # a burst landed between pump() returning and this
                    # lock acquire (its notify found no waiter): flush
                    # now instead of sleeping out the deadline. Checked
                    # under _wake, so later submits cannot be missed —
                    # their notify blocks until wait() releases it.
                    continue
                deadline = self.scheduler.next_deadline()
                if deadline is None:
                    # idle: block until a submit/close notifies (no
                    # polling — notify cannot be missed, it needs this
                    # lock, held until wait() releases it)
                    self._wake.wait()
                else:
                    delay = deadline - time.monotonic()
                    if delay > 0:
                        self._wake.wait(delay)
        # drain happens in close(), after this thread has joined — one
        # executor at a time, and a never-started worker drains too

    # -- graph hot-swap ----------------------------------------------------

    def swap_graph(self, version=None, *, rows=None, cols=None,
                   weights=None, **build_kw) -> dict:
        """Atomically replace the served graph while the server keeps
        running: in-flight batches finish on the OLD version (the swap
        waits on the engine's execution lock), queued and future
        requests execute on the new one, and the plan cache survives
        (same-shape versions: zero retraces). Pass either a prebuilt
        ``GraphVersion`` (``engine.build_version(...)`` — build it
        BEFORE calling, off the serving path) or a COO
        (``rows=``/``cols=``/``weights=``), which is built here, also
        outside the execution lock. Returns
        ``{"version", "swap_s", "nnz"}``."""
        if version is None:
            if rows is None or cols is None:
                raise ValueError(
                    "swap_graph needs a GraphVersion or rows=/cols="
                )
            version = self.engine.build_version(
                rows, cols, weights=weights, **build_kw
            )
        if self._wal is not None and version.wal_seq < 0:
            # an externally built version (hot-swap) carries no merge
            # lineage stamp: it supersedes everything MERGED so far,
            # while appended-but-unmerged ops still apply on top later
            version.wal_seq = self._wal_applied
        self.faults.check("engine.swap", version=version)
        swap_s = self.engine.swap(version)
        return {
            "version": self.engine.version_id,
            "swap_s": swap_s,
            "nnz": version.nnz,
        }

    # -- introspection -----------------------------------------------------

    def _last_error(self) -> dict | None:
        """The retained worker error as {repr, at} (shared by stats()
        and health())."""
        if self.last_worker_error is None:
            return None
        return {
            "repr": repr(self.last_worker_error),
            "at": self.last_worker_error_at,
        }

    def stats(self) -> dict:
        s = self.engine.stats()
        sch = self.scheduler
        now = time.monotonic()
        per_kind = {
            k: {
                "rejected": sch.rejected_kind.get(k, 0),
                "invalid": sch.invalid_kind.get(k, 0),
                "timeout": (
                    sch.timeout_kind.get(k, 0)
                    + self._timeout_exec.get(k, 0)
                ),
                "breaker_rejected": sch.breaker_rejected_kind.get(k, 0),
                "poisoned": self._poisoned.get(k, 0),
                "retried": self._retried.get(k, 0),
                **(
                    {"breaker": sch.breakers[k].describe(now)}
                    if k in sch.breakers else {}
                ),
            }
            for k in sch.kinds
        }
        s.update(
            tenant=self.tenant,
            queue_depth=sch.depth(),
            submitted=sch.submitted,
            rejected=sch.rejected,
            batches=self.batches,
            retry_batches=self.retry_batches,
            completed=self.completed,
            worker_errors=self.worker_errors,
            last_worker_error=self._last_error(),
            per_kind=per_kind,
            faults=self.faults.stats(),
            mean_occupancy=(
                self._occupancy_sum / self.batches if self.batches else None
            ),
            lane_widths=list(self.config.lane_widths),
            max_queue=self.config.max_queue,
            updates=self._update_stats(),
            durability=self._durability_stats(),
            slo=self.slo.describe() if self.slo is not None else None,
            flightrec=(
                self._recorder.describe()
                if self._recorder is not None else None
            ),
        )
        obs.gauge("serve.batches", self.batches)
        return s

    def _update_stats(self) -> dict:
        """Write-lane disposition: merge counts/mode split (the
        rebuild-amortization surface the mutate bench gates on)."""
        with self._upd_cond:
            pending = (
                self._upd_buffer.depth()
                if self._upd_buffer is not None else 0
            )
            buf = (
                self._upd_buffer.stats()
                if self._upd_buffer is not None else None
            )
        return {
            "submitted": self.updates_submitted,
            "invalid": self.updates_invalid,
            "merges": self.update_merges,
            "failed": self.update_failures,
            "pending": pending,
            "by_mode": dict(self._merge_modes),
            "merge_s_by_mode": {
                k: round(v, 6) for k, v in self._merge_s.items()
            },
            "buffer": buf,
        }

    def is_serving(self) -> bool:
        """Cheap routing-time liveness (round 16): an open front door
        whose worker (if ever started) is alive.  A never-started
        server counts as serving — the worker-less pump()-driven
        embedding.  The fleet's ``_route_order`` calls this per
        submit, so it must stay two attribute reads, not a full
        ``health()`` dict build."""
        if self.scheduler.closed:
            return False
        w = self._worker
        return w is None or w.is_alive()

    def quarantine(self, exc: Exception, timeout: float = 10.0) -> int:
        """Take a DEAD replica out of service (round 16, the fleet
        supervisor's cleanup): refuse new admissions, fail every
        pending read and buffered write future with ``exc`` — honest
        failure, never a silent drop; with a WAL attached the
        acknowledged writes themselves are NOT lost (they are on disk,
        and recovery/promotion replays them) — and stop the mutation
        and checkpointer threads.  Unlike ``close(drain=True)`` this
        never executes anything: the worker is presumed dead and the
        engine's state untrustworthy to drive.  Returns futures
        failed."""
        self.scheduler.close()
        with self._wake:
            self._stop = True
            self._wake.notify_all()
        n = self.scheduler.fail_pending(exc)
        with self._upd_cond:
            pending = len(self._upd_futs)
        self._stop_mutator(drain=False, timeout=timeout, abort_exc=exc)
        self._stop_checkpointer(timeout)
        if self._wal is not None:
            self._wal.close()
        obs.count("serve.fleet.quarantined")
        return n + pending

    def _durability_stats(self) -> dict | None:
        """WAL + checkpointer disposition (None when durability is
        off — the common case pays one attribute read)."""
        if self._wal is None:
            return None
        with self._ckpt_cond:
            since = self._merges_since_ckpt
        return {
            "dir": self._ckpt_dir,
            "wal": self._wal.stats(),
            "checkpoints": self.checkpoints,
            "checkpoint_failures": self.checkpoint_failures,
            "merges_since_checkpoint": since,
            "wal_frontier": self._wal_frontier,
        }

    def health(self) -> dict:
        """Liveness/readiness introspection, cheap enough to poll: the
        worker thread's state, per-kind breaker states, the retained
        last error, and the current graph version. ``status`` is
        ``"ok"`` (serving normally — including worker-less pump()-
        driven embedding, see ``worker_alive``), ``"degraded"`` (some
        kind's breaker is open or half-open — other kinds still
        serve), ``"down"`` (a started worker thread died: the front
        door is open but nothing drains), or ``"closed"``."""
        now = time.monotonic()
        breakers = {
            k: b.describe(now)
            for k, b in self.scheduler.breakers.items()
        }
        worker_alive = (
            self._worker is not None and self._worker.is_alive()
        )
        slo = self.slo.describe(now) if self.slo is not None else None
        closed = self.scheduler.closed
        if closed:
            status = "closed"
        elif self._worker is not None and not self._worker.is_alive():
            status = "down"  # started once, died/joined: door open,
            # nothing drains
        elif any(b["state"] != "closed" for b in breakers.values()):
            status = "degraded"
        elif slo is not None and slo["breached"]:
            # the SLO budget is burned through: everything still
            # serves, but the tenant's contract is being violated
            status = "degraded"
        else:
            status = "ok"
        return {
            "status": status,
            "tenant": self.tenant,
            "slo": slo,
            "flightrec_last_dump": (
                self._recorder.last_dump
                if self._recorder is not None else None
            ),
            "worker_alive": worker_alive,
            "closed": closed,
            "queue_depth": self.scheduler.depth(),
            "worker_errors": self.worker_errors,
            "worker_backoff_s": self._backoff_s,
            "last_worker_error": self._last_error(),
            "breakers": breakers,
            "graph_version": self.engine.version_id,
            "swaps": self.engine.swaps,
            "updates_pending": (
                self._upd_buffer.depth()
                if self._upd_buffer is not None else 0
            ),
            "mutator_alive": (
                self._mutator is not None and self._mutator.is_alive()
            ),
            "durable": self._wal is not None,
            "wal_frontier": (
                self._wal_frontier if self._wal is not None else None
            ),
            "checkpoints": self.checkpoints,
        }
