"""Server — the worker loop tying engine + scheduler + batcher together.

``submit()`` returns a ``concurrent.futures.Future`` immediately; a
single background worker thread owns ALL device execution (one
execution stream, like one TPU), waking on submissions and flush
deadlines, popping ready batches, padding them into lane buckets, and
scattering lane results back to futures. ``submit_many`` is the bulk
front door; ``stats()`` surfaces queue depth, batch occupancy, plan
cache and trace counts without needing obs enabled.

Usage::

    engine = GraphEngine.from_coo(grid, rows, cols, n)
    with engine.serve(ServeConfig(lane_widths=(1, 4, 16))) as srv:
        srv.warmup()                      # pre-trace every lane bucket
        f = srv.submit("bfs", root=7)
        print(f.result()["levels"][:10])
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
from concurrent.futures import Future

from .. import obs
from . import batcher
from .scheduler import BackpressureError, Scheduler, ServeConfig


class Server:
    """In-process query server over one ``GraphEngine``."""

    def __init__(self, engine, config: ServeConfig | None = None):
        self.engine = engine
        self.config = config or ServeConfig()
        self.scheduler = Scheduler(
            self.config, engine.nrows, engine.kinds()
        )
        self._wake = threading.Condition()
        self._stop = False
        self._worker: threading.Thread | None = None
        self.batches = 0
        self.completed = 0
        self.worker_errors = 0
        self.last_worker_error: Exception | None = None
        self._occupancy_sum = 0.0

    # -- lifecycle ---------------------------------------------------------

    def warmup(self, kinds=None, widths=None) -> dict:
        """Warm every (kind, lane width) plan the configured buckets can
        produce — after this, steady-state serving never traces."""
        return self.engine.warmup(
            kinds=kinds,
            widths=tuple(widths or self.config.lane_widths),
        )

    def start(self) -> "Server":
        if self.scheduler.closed:
            # close() is final (admissions are refused forever); a
            # restarted worker could never receive work
            raise RuntimeError(
                "serve.Server is closed; build a new one via "
                "engine.serve()"
            )
        if self._worker is None or not self._worker.is_alive():
            self._stop = False
            self._worker = threading.Thread(
                target=self._loop, name="combblas-serve", daemon=True
            )
            self._worker.start()
        return self

    def close(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Close the front door (subsequent submits raise — a closed
        server must never strand a future) and stop the worker;
        ``drain=True`` executes everything still queued first (in the
        CALLER's thread, after the worker has joined — so it also
        drains a server whose worker was never started), else pending
        requests fail with a shutdown error."""
        self.scheduler.close()  # admissions refused from here on
        with self._wake:
            self._stop = True
            self._wake.notify_all()
        if self._worker is not None:
            self._worker.join(timeout)
            if self._worker.is_alive():
                # the engine has ONE execution thread; draining from
                # this thread while the worker still runs would race
                # it — surface the stuck worker instead
                raise TimeoutError(
                    f"serve worker did not stop within {timeout}s; "
                    "queue not drained (call close() again later)"
                )
            self._worker = None
        if drain:
            while self.scheduler.depth():
                self.pump(force=True)
        else:
            self.scheduler.fail_pending(
                RuntimeError("serve.Server closed without drain")
            )

    def __enter__(self) -> "Server":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # -- front door --------------------------------------------------------

    def submit(self, kind: str, root, timeout_s: float | None = None
               ) -> Future:
        """Admit one single-root query. Raises ``BackpressureError``
        when the bounded queue is full (reject + retry-after, never
        unbounded blocking); malformed roots come back as failed
        futures (error isolation — see scheduler.submit)."""
        fut = self.scheduler.submit(kind, root, timeout_s=timeout_s)
        with self._wake:
            self._wake.notify_all()
        return fut

    def submit_many(self, kind: str, roots, timeout_s: float | None = None
                    ) -> list[Future]:
        """Bulk submit; stops at the first backpressure rejection and
        fails the REMAINING requests' futures with it (the caller sees
        exactly which prefix was admitted — one future per root, in
        order, generators included)."""
        roots = list(roots)  # single materialization: generator-safe
        out: list[Future] = []
        for i, r in enumerate(roots):
            try:
                out.append(
                    self.scheduler.submit(kind, r, timeout_s=timeout_s)
                )
            except (BackpressureError, RuntimeError) as e:
                # backpressure OR a concurrent close(): either way the
                # caller must still get one future per root — the
                # admitted prefix's results stay reachable
                for _ in roots[i:]:
                    f = Future()
                    f.set_exception(
                        BackpressureError(
                            self.scheduler.depth(), e.retry_after_s
                        )
                        if isinstance(e, BackpressureError) else e
                    )
                    out.append(f)
                break
        with self._wake:
            self._wake.notify_all()
        return out

    # -- worker ------------------------------------------------------------

    def _execute_batches(self, ready) -> None:
        for reqs in ready:
            # whole-batch guard: these requests are already popped, so
            # ANY failure (assemble, engine, scatter) must settle their
            # futures — a stranded future blocks its caller forever
            try:
                sources = batcher.assemble(
                    reqs, self.config.lane_widths
                )
                self.batches += 1
                self._occupancy_sum += len(reqs) / len(sources)
                result = self.engine.execute(reqs[0].kind, sources)
                self.completed += batcher.scatter(reqs, result)
            except Exception as e:  # failure fails THIS batch only
                batcher.fail(reqs, e)

    def pump(self, force: bool = False) -> int:
        """One synchronous scheduling step (the worker's body, callable
        directly for deterministic tests / worker-less embedding):
        execute every batch currently due. Returns batches executed."""
        ready = self.scheduler.pop_ready(force=force)
        self._execute_batches(ready)
        return len(ready)

    def _loop(self) -> None:
        while True:
            with self._wake:
                if self._stop:
                    break
            # pump BEFORE sleeping: requests that arrived while the
            # previous batch executed (their notify found no waiter)
            # may already fill a lane bucket — flush-on-full must not
            # wait out the deadline
            try:
                if self.pump():
                    continue
            except Exception as e:  # the worker must outlive any one
                # pump: a dead worker with an open front door would
                # admit requests whose futures never complete. The
                # error is RETAINED and printed — an obs counter alone
                # would vanish with telemetry off (the default)
                self.worker_errors += 1
                self.last_worker_error = e
                obs.count("serve.worker.errors")
                traceback.print_exc(file=sys.stderr)
                time.sleep(0.05)
                continue
            with self._wake:
                if self._stop:
                    break
                if self.scheduler.has_ready():
                    # a burst landed between pump() returning and this
                    # lock acquire (its notify found no waiter): flush
                    # now instead of sleeping out the deadline. Checked
                    # under _wake, so later submits cannot be missed —
                    # their notify blocks until wait() releases it.
                    continue
                deadline = self.scheduler.next_deadline()
                if deadline is None:
                    # idle: block until a submit/close notifies (no
                    # polling — notify cannot be missed, it needs this
                    # lock, held until wait() releases it)
                    self._wake.wait()
                else:
                    delay = deadline - time.monotonic()
                    if delay > 0:
                        self._wake.wait(delay)
        # drain happens in close(), after this thread has joined — one
        # executor at a time, and a never-started worker drains too

    # -- introspection -----------------------------------------------------

    def stats(self) -> dict:
        s = self.engine.stats()
        s.update(
            queue_depth=self.scheduler.depth(),
            submitted=self.scheduler.submitted,
            rejected=self.scheduler.rejected,
            batches=self.batches,
            completed=self.completed,
            worker_errors=self.worker_errors,
            mean_occupancy=(
                self._occupancy_sum / self.batches if self.batches else None
            ),
            lane_widths=list(self.config.lane_widths),
            max_queue=self.config.max_queue,
        )
        obs.gauge("serve.batches", self.batches)
        return s
