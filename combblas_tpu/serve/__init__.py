"""``combblas_tpu.serve`` — batched, backpressured graph-query serving
on a warm mesh.

The kernel library answers "how fast is one batch"; this subsystem
answers "how do a million independent single-root queries BECOME
batches". Four layers (docs/serving.md has the full architecture):

1. **engine** (`engine.py`) — ``GraphEngine``: one loaded graph
   (EllParMat + weighted/normalized/transposed twins, CSC companion,
   degree vectors) and a shape-bucketed plan cache, pre-warmed by
   ``warmup()`` so steady-state requests never trace or compile.
2. **batcher** (`batcher.py`) — lane-bucket assembly: coalesce
   single-root BFS/SSSP/PageRank/BC requests into the nearest
   power-of-two lane width, pad with ``models.PAD_ROOT``, scatter
   per-lane results back to request futures (pad lanes can never leak).
3. **scheduler** (`scheduler.py`) — bounded queue with
   reject-with-retry-after admission control, per-kind flush deadlines,
   per-request timeouts, and per-request error isolation.
4. **api** (`api.py`) — ``Server``: ``submit()/submit_many()/stats()``
   plus the single worker thread that owns the execution stream, the
   poisoned-batch bisection retrier, execution-time deadline
   enforcement, ``health()``, ``swap_graph()`` (atomic graph-version
   hot-swap, plan cache surviving), and the WRITE lane —
   ``submit_update()`` + a mutation thread coalescing edge deltas into
   incremental merges (``combblas_tpu.dynamic``, docs/dynamic.md)
   off the execution lock, reads staying hot throughout.
5. **faults** (`faults.py`) — deterministic fault injection: named
   failure points threaded through the worker path, armed with
   scripted/seeded/predicate rules so every recovery path (bisection,
   per-kind circuit breakers, worker backoff, swap rollback) is
   testable and chaos-benchable.
6. **pool** (`pool.py`, round 14) — ``EnginePool``/``PoolServer``:
   many resident tenant graphs behind one device — tenant → engine
   routing, byte-accounted LRU eviction (host COO retained, re-admit
   rebuilds bit-exact), per-tenant breakers/SLOs/fault injectors, and
   one worker thread arbitrated by weighted deficit-round-robin
   (reads AND write merges charge the tenant's share).
7. **fleet** (`fleet.py`, rounds 14/16) — ``FleetRouter``: N replica
   servers behind one front door sharing ONE warm plan store —
   least-loaded routing with spillover (dead/closed/draining replicas
   attract no traffic), writes routed to a home replica and fanned
   out through the atomic swap, warm starts from
   ``utils.checkpoint.save_version`` GraphVersion snapshots; plus the
   round-16 self-healing layer: a supervisor thread detecting dead
   replica workers, quarantine (pending futures failed honestly),
   rebuild-from-checkpoint+WAL replacement, home PROMOTION at the
   write-ahead log's seqno frontier, ``drain``/``rolling_restart``,
   and bounded read retry on the next-best replica.  The durability
   substrate (``dynamic/wal.py`` WAL + ``Server``'s background
   checkpointer + ``from_recovery``) is docs/serving.md "Durability &
   self-healing".
8. **procfleet** (`procfleet.py` + `_procworker.py` + `ipc.py` +
   `policy.py`, round 17) — ``ProcessFleet``: the same fleet with
   REAL crash domains — each replica is an OS subprocess hosting a
   ``Server`` on its own JAX runtime (no shared exec lock: honest
   replica parallelism) behind a length-prefixed JSON IPC channel
   with per-request deadlines.  Routing/supervision policy is shared
   with ``FleetRouter`` via ``policy.py``; liveness is process-level
   (``Popen.poll``, broken pipe, heartbeat timeout — a SIGSTOPped
   replica is detected as a HANG and routed around), replacements
   respawn warm from checkpoint+WAL, the dead-home promotion happens
   over IPC at the WAL frontier, versions fan out as checkpoint
   files (never pickled arrays), and ``ProcessFaultPlan`` scripts
   real SIGKILL/SIGSTOP chaos deterministically.
9. **net** (`net/`, round 19) — ``NetFrontend``/``NetClient``: the
   TCP front door — a versioned request/reply protocol over the
   shared frame codec (``frame.py``, factored out of ``ipc.py`` so
   procfleet and net speak ONE codec over two transports), fronting
   any backend above: tenant-header routing into the pool, wire
   deadlines propagating into the SLO budget, the whole error
   taxonomy mapped onto typed protocol status codes (a rejection is
   a wire reply, never a dropped connection), and the open-loop
   Poisson load harness (``net/loadgen.py``, ``BENCH_SERVE_NET=1``)
   whose latencies are measured from scheduled arrival time — no
   coordinated omission.
10. **shard** (`shard.py` + `_shardworker.py`, round 20) —
   ``ShardedEngine``: ONE huge graph partitioned over N slice
   processes (contiguous row slabs, each a rectangular EllParMat on
   its own JAX runtime — per-host resident bytes ~1/p), duck-typing
   ``GraphEngine`` so the batcher/scheduler/api/net stack above runs
   UNCHANGED on top.  Queries execute as router-driven
   bulk-synchronous hop loops (the same jitted step bodies as the
   unsharded while_loop — bfs/sssp answers bit-exact); writes run a
   two-phase per-slice WAL protocol under a VECTOR checkpoint
   frontier; a dead slice is quarantined, respawned from its slab
   snapshot + WAL suffix, and re-joined while the OTHER slices keep
   serving (docs/serving.md "Sharded serving").

Everything is wired into ``combblas_tpu.obs`` (queue-depth gauge,
occupancy/padding-waste/latency histograms, plan-cache and
``trace.serve`` counters) and measured by ``benchmarks/serve_bench.py``
against the one-call-per-query baseline.
"""

from .batcher import Request, assemble, bucket_width, scatter
from .engine import KINDS, GraphEngine, GraphVersion
from .faults import (
    FAULT_POINTS,
    FaultInjector,
    InjectedFault,
    ProcessFaultPlan,
)
from .scheduler import (
    BackpressureError,
    CircuitBreaker,
    CircuitBreakerOpen,
    DeficitRoundRobin,
    Scheduler,
    ServeConfig,
)
from .api import Server
from .pool import EnginePool, PoolServer
from .fleet import FleetRouter, ReplicaDeadError
from .procfleet import IpcTimeoutError, ProcessFleet, ReplicaProc
from .net import NetClient, NetFrontend
from .shard import ShardedEngine, ShardedGraphVersion, plan_partition
from .slo import ErrorBudget

__all__ = [
    "GraphEngine", "GraphVersion", "Server", "ServeConfig", "Scheduler",
    "BackpressureError", "CircuitBreaker", "CircuitBreakerOpen",
    "DeficitRoundRobin", "EnginePool", "PoolServer", "FleetRouter",
    "ReplicaDeadError",
    "ProcessFleet", "ReplicaProc", "IpcTimeoutError",
    "NetFrontend", "NetClient",
    "ShardedEngine", "ShardedGraphVersion", "plan_partition",
    "FaultInjector", "InjectedFault", "ProcessFaultPlan",
    "FAULT_POINTS", "ErrorBudget",
    "Request", "KINDS",
    "bucket_width", "assemble", "scatter",
]
