"""Deadline-driven micro-batcher: coalesce single-root queries into the
fixed-width lanes the batch kernels want.

The batch kernels (``models.bfs.bfs_batch``, ``models.sssp.sssp_batch``,
``models.pagerank.pagerank_batch``, ``models.bc.bc_batch_dense_lanes``)
amortize the per-index gather cost across W payload lanes — but they are
compiled per (kind, W, dtype), so serving arbitrary request counts
directly would retrace constantly. The batcher therefore rounds every
flush UP to the nearest configured lane bucket (powers of two by
default), pads the spare lanes with ``models.PAD_ROOT`` (inert by the
kernels' live-lane guard), and scatters per-lane results back to the
issuing requests — pad lanes are structurally incapable of leaking into
user results because scatter walks the REQUEST list, never the lane
array.

This is the batching half of a continuous-batching inference server:
lane buckets play the role of padded sequence buckets, the pad sentinel
the role of the pad token, and occupancy/padding-waste histograms
(``serve.batch.occupancy`` / ``serve.batch.padding_waste``) make the
bucket-policy cost measurable.
"""

from __future__ import annotations

import dataclasses
import time
from concurrent.futures import Future, InvalidStateError

import numpy as np

from .. import obs
from ..models import PAD_ROOT


def expire(req: "Request", where: str, on_timeout=None) -> bool:
    """Settle an expired request with ``TimeoutError`` — the ONE place
    the timeout message, the ``serve.requests{status=timeout}`` counter,
    and the optional per-kind accounting hook live (three enforcement
    points share it: the queue sweep, the pre-execution drop, and the
    during-execution scatter check). Returns whether WE settled it."""
    if settle(req.future, exc=TimeoutError(
        f"request {req.rid} ({req.kind} root={req.root}) {where}"
    )):
        obs.count("serve.requests", kind=req.kind, status="timeout")
        if req.trace is not None:
            req.trace.finish(status="timeout", stage="expired")
        if on_timeout is not None:
            on_timeout(req)
        return True
    return False


def settle(fut: Future, *, result=None, exc: Exception | None = None
           ) -> bool:
    """``set_result``/``set_exception`` tolerating a concurrent
    client-side ``cancel()`` (these futures never enter RUNNING, so a
    caller's cancel always wins the done()-check race). Returns whether
    the future was settled by US."""
    try:
        if exc is not None:
            fut.set_exception(exc)
        else:
            fut.set_result(result)
        return True
    except InvalidStateError:
        return False


@dataclasses.dataclass
class Request:
    """One in-flight single-root query."""

    rid: int
    kind: str
    root: int
    future: Future
    submitted_at: float
    deadline: float | None = None  # absolute; None = no timeout
    attempts: int = 0  # FAILING executions ridden (retry-budget meter)
    trace: object = None  # sampled obs.trace.RequestTrace, or None

    def expired(self, now: float) -> bool:
        return self.deadline is not None and now >= self.deadline


def bucket_width(count: int, widths: tuple[int, ...]) -> int:
    """Smallest configured lane width >= count (the shape bucket this
    flush compiles/executes under); counts past the widest bucket clamp
    to it — the scheduler flushes the remainder in a later batch."""
    if count <= 0:
        raise ValueError("bucket_width needs a positive request count")
    for w in widths:
        if count <= w:
            return w
    return widths[-1]


def assemble(requests: list[Request], widths: tuple[int, ...],
             record: bool = True) -> np.ndarray:
    """Roots of ``requests`` as one int32 lane vector, padded with
    ``PAD_ROOT`` up to the bucket width. The batch must FIT the widest
    bucket — chunking an oversized backlog is the scheduler's job
    (``pop_ready`` flushes at most the widest width per batch); a
    direct caller exceeding it gets a ValueError, never a silent
    truncation. Records the occupancy and padding-waste histograms
    unless ``record=False`` (bisection-retry sub-batches: re-recording
    them would misread fault recovery as poor coalescing)."""
    W = bucket_width(len(requests), widths)
    if len(requests) > W:
        raise ValueError(
            f"{len(requests)} requests exceed the widest lane bucket {W}"
        )
    sources = np.full(W, PAD_ROOT, np.int32)
    for k, r in enumerate(requests):
        sources[k] = r.root
    if record:
        kind = requests[0].kind
        obs.observe(
            "serve.batch.occupancy", len(requests) / W, kind=kind
        )
        obs.observe(
            "serve.batch.padding_waste", W - len(requests), kind=kind
        )
    return sources


def scatter(requests: list[Request], result: dict,
            now: float | None = None, on_timeout=None,
            on_ok=None, on_error=None) -> int:
    """Hand each request its own lane of ``result`` (the engine's
    column-sliced output dict). Pad lanes are never touched: iteration
    is over the request list (lane k belongs to requests[k]); the
    remaining lanes simply have no owner. Requests whose future is
    already settled (timeout/cancel) are skipped; a request that
    expired DURING execution is timed out here (``on_timeout(req)``,
    when given, lets the server keep its per-kind accounting in step
    with the obs counter; ``on_ok(req)``/``on_error(req)`` are the
    success- and lane-error-side twins — the SLO budget's good/bad
    hooks, so a per-lane scatter failure burns the budget like any
    other user-visible error). Returns the number of futures
    completed."""
    now = time.monotonic() if now is None else now
    done = 0
    for k, req in enumerate(requests):
        if req.future.done():
            continue
        if req.expired(now):
            expire(req, "missed its deadline during execution",
                   on_timeout)
            continue
        try:
            # lane COPIES, not views: a retained view would pin the
            # whole [n, W] batch buffer for one request's lifetime
            lane = {
                key: (
                    np.ascontiguousarray(val[..., k])
                    if isinstance(val, np.ndarray) else val
                )
                for key, val in result.items()
            }
            if settle(req.future, result=lane):
                done += 1
                obs.count("serve.requests", kind=req.kind, status="ok")
                obs.observe(
                    "serve.request.latency_s", now - req.submitted_at,
                    kind=req.kind,
                )
                if req.trace is not None:
                    # the scatter stage closes the sampled trace: its
                    # stage sum now telescopes to the e2e latency
                    req.trace.finish(status="ok", stage="scatter")
                if on_ok is not None:
                    on_ok(req)
        except Exception as e:  # isolate: one bad lane never kills peers
            settle(req.future, exc=e)
            obs.count("serve.requests", kind=req.kind, status="error")
            if req.trace is not None:
                req.trace.finish(status="error", stage="scatter")
            if on_error is not None:
                on_error(req)
    return done


