"""``NetClient`` — the blocking wire client for the net front door.

One TCP connection, one background reader thread matching pipelined
replies to futures by ``id``.  The blocking calls
(``submit``/``submit_many``/``submit_update``/``stats``/``health``)
wrap the ``*_nowait`` future primitives the open-loop load generator
drives directly (an open-loop harness must SEND on schedule, never
block on completions — ``submit_nowait`` is that send).

Typed failures come back as the SAME exception types an in-process
caller sees (``protocol.wire_exception``): a ``backpressure`` reply
raises ``BackpressureError`` with its ``retry_after_s`` hint intact.
A dropped connection fails every pending future with
``ConnectionError`` — stranded futures are impossible by construction
(the reader thread owns the pending map's teardown).
"""

from __future__ import annotations

import itertools
import socket
import threading
from concurrent.futures import Future

from ..frame import Channel
from . import protocol as P


class NetClient:
    """Blocking client for one ``NetFrontend`` connection."""

    def __init__(self, host: str, port: int, *,
                 tenant: str | None = None,
                 connect_timeout_s: float = 10.0):
        sock = socket.create_connection(
            (host, port), timeout=connect_timeout_s
        )
        self.ch = Channel(sock, peer="netclient")
        self.tenant = tenant
        self._pending: dict[int, Future] = {}
        self._plock = threading.Lock()
        self._rid = itertools.count(1)
        self._closed = False
        self.ch.send({
            "v": P.PROTOCOL_VERSION, "op": "hello", "id": 0,
            "tenant": tenant,
        })
        hello = self.ch.recv(timeout=connect_timeout_s)
        if hello.get("status") != P.ST_OK:
            self.ch.close()
            raise P.wire_exception(hello)
        self.server_pooled = bool(hello.get("pooled"))
        self._reader = threading.Thread(
            target=self._read_loop, daemon=True,
            name=f"combblas-net-client:{port}",
        )
        self._reader.start()

    # -- reader ------------------------------------------------------------

    def _read_loop(self) -> None:
        while True:
            try:
                m = self.ch.recv(timeout=0.25)
            except socket.timeout:
                continue
            except Exception as e:
                self._fail_all(ConnectionError(
                    "connection closed" if self._closed
                    else f"server gone: {e}"
                ))
                return
            if not isinstance(m, dict):
                continue
            with self._plock:
                fut = self._pending.pop(m.get("id"), None)
            if fut is None:
                continue  # reply for an id we never sent (or re-sent)
            if m.get("status") == P.ST_OK:
                if not fut.set_running_or_notify_cancel():
                    continue
                if "results" in m:
                    fut.set_result(m["results"])
                else:
                    fut.set_result(m.get("result"))
            else:
                if fut.set_running_or_notify_cancel():
                    fut.set_exception(P.wire_exception(m))

    def _fail_all(self, exc: Exception) -> None:
        with self._plock:
            pending = list(self._pending.values())
            self._pending.clear()
        for f in pending:
            if f.set_running_or_notify_cancel():
                f.set_exception(exc)

    # -- send primitives (open-loop harness drives these) ------------------

    def _send(self, msg: dict) -> Future:
        fut: Future = Future()
        mid = next(self._rid)
        msg["id"] = mid
        with self._plock:
            if self._closed:
                raise ConnectionError("client closed")
            self._pending[mid] = fut
        try:
            self.ch.send(msg)
        except Exception as e:
            with self._plock:
                self._pending.pop(mid, None)
            raise ConnectionError(f"send failed: {e}") from e
        return fut

    def submit_nowait(self, kind: str, root,
                      deadline_s: float | None = None) -> Future:
        """Send one query WITHOUT waiting; the Future resolves to the
        result dict or raises the typed rejection."""
        msg: dict = {"op": "submit", "kind": kind, "root": root}
        if deadline_s is not None:
            msg["deadline_s"] = deadline_s
        return self._send(msg)

    def submit_many_nowait(self, kind: str, roots,
                           deadline_s: float | None = None) -> Future:
        msg: dict = {
            "op": "submit_many", "kind": kind, "roots": list(roots),
        }
        if deadline_s is not None:
            msg["deadline_s"] = deadline_s
        return self._send(msg)

    def submit_update_nowait(self, ops) -> Future:
        return self._send({
            "op": "submit_update", "ops": [list(o) for o in ops],
        })

    # -- blocking API ------------------------------------------------------

    def submit(self, kind: str, root, deadline_s: float | None = None,
               timeout_s: float = 120.0) -> dict:
        return self.submit_nowait(
            kind, root, deadline_s=deadline_s
        ).result(timeout=timeout_s)

    def submit_many(self, kind: str, roots,
                    deadline_s: float | None = None,
                    timeout_s: float = 120.0) -> list[dict]:
        """One entry per root, in order: ``{"status": "ok", "result":
        {...}}`` or the typed wire-error dict — per-root failure
        isolation survives the wire without torn batches."""
        return self.submit_many_nowait(
            kind, roots, deadline_s=deadline_s
        ).result(timeout=timeout_s)

    def submit_update(self, ops, timeout_s: float = 120.0) -> dict:
        return self.submit_update_nowait(ops).result(timeout=timeout_s)

    def stats(self, timeout_s: float = 30.0) -> dict:
        return self._send({"op": "stats"}).result(timeout=timeout_s)

    def health(self, timeout_s: float = 30.0) -> dict:
        return self._send({"op": "health"}).result(timeout=timeout_s)

    def ping(self, timeout_s: float = 30.0) -> dict:
        return self._send({"op": "ping"}).result(timeout=timeout_s)

    @property
    def pending(self) -> int:
        with self._plock:
            return len(self._pending)

    def close(self) -> None:
        """Close the socket; pending futures fail with
        ``ConnectionError`` (reader-thread teardown — never stranded)."""
        self._closed = True
        self.ch.close()
        self._reader.join(timeout=5.0)
        self._fail_all(ConnectionError("client closed"))

    def __enter__(self) -> "NetClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
