"""``NetClient`` — the blocking wire client for the net front door.

One TCP connection, one background reader thread matching pipelined
replies to futures by ``id``.  The blocking calls
(``submit``/``submit_many``/``submit_update``/``stats``/``health``)
wrap the ``*_nowait`` future primitives the open-loop load generator
drives directly (an open-loop harness must SEND on schedule, never
block on completions — ``submit_nowait`` is that send).

Typed failures come back as the SAME exception types an in-process
caller sees (``protocol.wire_exception``): a ``backpressure`` reply
raises ``BackpressureError`` with its ``retry_after_s`` hint intact.
A dropped connection fails every pending future with
``ConnectionError`` — stranded futures are impossible by construction
(the reader thread owns its connection generation's teardown).

Round 20 (the ROADMAP front-door follow-up): the BLOCKING calls
retry.  A ``BackpressureError`` sleeps the server's own
``retry_after_s`` hint (capped) and resends; a dropped connection
reconnects — new socket, new hello, new reader — with bounded
exponential backoff and resends.  Retry budgets are per-call
(``max_retries``, default 3; ``max_retries=0`` restores the old
fail-fast behavior).  Two deliberate exclusions:

* the ``*_nowait`` primitives never retry — the open-loop harness
  measures the wire as it is, and silent resends would falsify its
  availability numbers;
* ``submit_update`` retries ONLY when the send itself failed (the
  request provably never left this process).  A write that died
  IN FLIGHT may have been applied — blindly resending a
  non-idempotent insert/delete batch could double-apply it, so that
  ``ConnectionError`` surfaces to the caller, who owns idempotency.
"""

from __future__ import annotations

import itertools
import socket
import threading
import time
from concurrent.futures import Future

from ..frame import Channel
from ..scheduler import BackpressureError
from . import protocol as P


class NetClient:
    """Blocking client for one ``NetFrontend`` connection."""

    def __init__(self, host: str, port: int, *,
                 tenant: str | None = None,
                 connect_timeout_s: float = 10.0,
                 max_retries: int = 3,
                 backoff_s: float = 0.05,
                 max_backoff_s: float = 2.0):
        self.host = host
        self.port = port
        self.tenant = tenant
        self.connect_timeout_s = connect_timeout_s
        self.max_retries = int(max_retries)
        self.backoff_s = float(backoff_s)
        self.max_backoff_s = float(max_backoff_s)
        # pending: id -> (future, connection generation) — replies pop
        # by id; a dying reader fails ONLY its own generation, so a
        # reconnect's fresh in-flights can never be torn down by the
        # old connection's teardown racing in behind it
        self._pending: dict[int, tuple[Future, int]] = {}
        self._plock = threading.Lock()
        self._rid = itertools.count(1)
        self._closed = False
        self._conn_lock = threading.Lock()
        self._gen = 0
        self._conn_dead = False
        self.reconnects = 0
        self.ch: Channel = None  # set by _connect_locked
        with self._conn_lock:
            self._connect_locked()

    # -- connection lifecycle ----------------------------------------------

    def _connect_locked(self) -> None:
        """(Re)establish the connection: socket, hello, reader.  Caller
        holds ``_conn_lock``."""
        sock = socket.create_connection(
            (self.host, self.port), timeout=self.connect_timeout_s
        )
        ch = Channel(sock, peer="netclient")
        try:
            ch.send({
                "v": P.PROTOCOL_VERSION, "op": "hello", "id": 0,
                "tenant": self.tenant,
            })
            hello = ch.recv(timeout=self.connect_timeout_s)
        except Exception as e:
            ch.close()
            raise ConnectionError(f"hello failed: {e}") from e
        if hello.get("status") != P.ST_OK:
            ch.close()
            raise P.wire_exception(hello)
        self.server_pooled = bool(hello.get("pooled"))
        self.ch = ch
        self._gen += 1
        self._conn_dead = False
        reader = threading.Thread(
            target=self._read_loop, args=(ch, self._gen), daemon=True,
            name=f"combblas-net-client:{self.port}",
        )
        reader.start()
        self._reader = reader

    def _ensure_connected(self) -> None:
        """Reconnect if the current connection is known-dead (send
        failure or reader teardown); a healthy connection is a no-op,
        and concurrent callers collapse into one reconnect."""
        with self._conn_lock:
            if self._closed:
                raise ConnectionError("client closed")
            if not self._conn_dead:
                return
            try:
                self.ch.close()
            except Exception:
                pass
            self._connect_locked()
            self.reconnects += 1

    # -- reader ------------------------------------------------------------

    def _read_loop(self, ch: Channel, gen: int) -> None:
        while True:
            try:
                m = ch.recv(timeout=0.25)
            except socket.timeout:
                continue
            except Exception as e:
                self._conn_dead = True
                self._fail_all(ConnectionError(
                    "connection closed" if self._closed
                    else f"server gone: {e}"
                ), gen=gen)
                return
            if not isinstance(m, dict):
                continue
            with self._plock:
                ent = self._pending.pop(m.get("id"), None)
            if ent is None:
                continue  # reply for an id we never sent (or re-sent)
            fut, _g = ent
            if m.get("status") == P.ST_OK:
                if not fut.set_running_or_notify_cancel():
                    continue
                if "results" in m:
                    fut.set_result(m["results"])
                else:
                    fut.set_result(m.get("result"))
            else:
                if fut.set_running_or_notify_cancel():
                    fut.set_exception(P.wire_exception(m))

    def _fail_all(self, exc: Exception, gen: int | None = None) -> None:
        """Fail pending futures — all of them (close), or only one
        connection generation's (a dying reader must not tear down a
        successor's in-flights)."""
        with self._plock:
            doomed = [
                (mid, f) for mid, (f, g) in self._pending.items()
                if gen is None or g == gen
            ]
            for mid, _f in doomed:
                self._pending.pop(mid, None)
        for _mid, f in doomed:
            if f.set_running_or_notify_cancel():
                f.set_exception(exc)

    # -- send primitives (open-loop harness drives these) ------------------

    def _send(self, msg: dict) -> Future:
        fut: Future = Future()
        mid = next(self._rid)
        msg["id"] = mid
        ch = self.ch
        with self._plock:
            if self._closed:
                raise ConnectionError("client closed")
            self._pending[mid] = (fut, self._gen)
        try:
            ch.send(msg)
        except Exception as e:
            with self._plock:
                self._pending.pop(mid, None)
            self._conn_dead = True
            raise ConnectionError(f"send failed: {e}") from e
        return fut

    def submit_nowait(self, kind: str, root,
                      deadline_s: float | None = None) -> Future:
        """Send one query WITHOUT waiting; the Future resolves to the
        result dict or raises the typed rejection.  Never retries —
        the open-loop contract."""
        msg: dict = {"op": "submit", "kind": kind, "root": root}
        if deadline_s is not None:
            msg["deadline_s"] = deadline_s
        return self._send(msg)

    def submit_many_nowait(self, kind: str, roots,
                           deadline_s: float | None = None) -> Future:
        msg: dict = {
            "op": "submit_many", "kind": kind, "roots": list(roots),
        }
        if deadline_s is not None:
            msg["deadline_s"] = deadline_s
        return self._send(msg)

    def submit_update_nowait(self, ops) -> Future:
        return self._send({
            "op": "submit_update", "ops": [list(o) for o in ops],
        })

    # -- the retry loop -----------------------------------------------------

    def _call_retrying(self, send_fn, timeout_s: float, *,
                       retry_inflight: bool = True):
        """Send + wait with the bounded retry policy (module
        docstring): backpressure sleeps the server's hint; a dead
        connection reconnects with exponential backoff.  A request
        that FAILED IN FLIGHT is resent only when ``retry_inflight``
        (reads are; writes are not — idempotency is the caller's)."""
        backoff = self.backoff_s
        attempt = 0
        while True:
            sent = False
            try:
                fut = send_fn()
                sent = True
                return fut.result(timeout=timeout_s)
            except BackpressureError as e:
                if attempt >= self.max_retries:
                    raise
                # the server's own capacity estimate beats any local
                # guess; 0/None degrades to the local backoff ladder
                delay = e.retry_after_s or backoff
                time.sleep(min(delay, self.max_backoff_s))
            except ConnectionError:
                if (
                    self._closed
                    or attempt >= self.max_retries
                    or (sent and not retry_inflight)
                ):
                    raise
                time.sleep(min(backoff, self.max_backoff_s))
                self._ensure_connected()
            attempt += 1
            backoff = min(backoff * 2, self.max_backoff_s)

    # -- blocking API ------------------------------------------------------

    def submit(self, kind: str, root, deadline_s: float | None = None,
               timeout_s: float = 120.0) -> dict:
        return self._call_retrying(
            lambda: self.submit_nowait(kind, root,
                                       deadline_s=deadline_s),
            timeout_s,
        )

    def submit_many(self, kind: str, roots,
                    deadline_s: float | None = None,
                    timeout_s: float = 120.0) -> list[dict]:
        """One entry per root, in order: ``{"status": "ok", "result":
        {...}}`` or the typed wire-error dict — per-root failure
        isolation survives the wire without torn batches."""
        return self._call_retrying(
            lambda: self.submit_many_nowait(kind, list(roots),
                                            deadline_s=deadline_s),
            timeout_s,
        )

    def submit_update(self, ops, timeout_s: float = 120.0) -> dict:
        ops = [list(o) for o in ops]
        return self._call_retrying(
            lambda: self.submit_update_nowait(ops), timeout_s,
            retry_inflight=False,
        )

    def stats(self, timeout_s: float = 30.0) -> dict:
        return self._call_retrying(
            lambda: self._send({"op": "stats"}), timeout_s
        )

    def health(self, timeout_s: float = 30.0) -> dict:
        return self._call_retrying(
            lambda: self._send({"op": "health"}), timeout_s
        )

    def ping(self, timeout_s: float = 30.0) -> dict:
        return self._call_retrying(
            lambda: self._send({"op": "ping"}), timeout_s
        )

    @property
    def pending(self) -> int:
        with self._plock:
            return len(self._pending)

    def close(self) -> None:
        """Close the socket; pending futures fail with
        ``ConnectionError`` (reader-thread teardown — never stranded)."""
        self._closed = True
        self.ch.close()
        self._reader.join(timeout=5.0)
        self._fail_all(ConnectionError("client closed"))

    def __enter__(self) -> "NetClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
