"""The network front door's versioned request/reply protocol (r19).

One TCP connection carries, over the shared frame codec
(``serve/frame.py`` — the same ``[4B len][4B header_len][JSON][blobs]``
frames the process fleet speaks):

1. a HELLO handshake — the client's first frame::

       {"v": 1, "op": "hello", "id": 0, "tenant": "web" | null}

   answered with ``{"id": 0, "status": "ok", "v": 1}`` or a TYPED
   rejection (protocol-version mismatch, unknown tenant, connection
   limit) — a refused connection gets a wire reply, never a silent
   close.  The auth-less ``tenant`` header routes every subsequent
   request on this connection to that ``PoolServer`` tenant; against a
   single-tenant backend (``Server``/``ProcessFleet``/``FleetRouter``)
   it is ignored.

2. pipelined requests — ``id`` correlates replies, which may arrive
   out of order (the backend batches and reorders freely)::

       {"id": 7, "op": "submit", "kind": "bfs", "root": 12,
        "deadline_s": 0.5}                      # deadline optional
       {"id": 8, "op": "submit_many", "kind": "bfs", "roots": [1, 2]}
       {"id": 9, "op": "submit_update", "ops": [["insert", u, v, w]]}
       {"id": 10, "op": "stats"} | {"op": "health"} | {"op": "ping"}

3. replies — ``{"id": n, "status": "ok", "result": {...}}`` (ndarray
   values ride the frame's binary section) or a typed rejection::

       {"id": n, "status": "backpressure", "error": "...",
        "retry_after_s": 0.01, "tenant": "web"}

``deadline_s`` is the request's END-TO-END budget in seconds from
server receipt; it propagates into the scheduler's per-request
timeout, where ``ServeConfig.slo_deadline_s`` still CAPS it (a wire
deadline may tighten the SLO budget, never loosen it).

The status codes are the PR 12 error taxonomy, bijectively — a client
sees the same exception types an in-process caller would:

=================  ====================================================
status             server-side exception / client-side raise
=================  ====================================================
``ok``             —
``backpressure``   ``BackpressureError`` (queue full; retry_after_s)
``breaker_open``   ``CircuitBreakerOpen`` (kind's breaker tripped)
``replica_dead``   ``ReplicaDeadError`` (every routed replica failed)
``timeout``        ``TimeoutError`` / ``IpcTimeoutError`` (deadline)
``invalid``        ``ValueError``/``KeyError`` (bad kind/root/tenant/op)
``unavailable``    anything else (server closing, internal failure)
=================  ====================================================

``breaker_open`` is checked BEFORE ``backpressure`` (it is a subclass)
so the more specific code wins.
"""

from __future__ import annotations

from ..policy import ReplicaDeadError
from ..procfleet import IpcTimeoutError
from ..scheduler import BackpressureError, CircuitBreakerOpen

#: Protocol version spoken by this build; hello frames carrying any
#: other version are rejected with ``invalid`` (naming both versions).
PROTOCOL_VERSION = 1

ST_OK = "ok"
ST_BACKPRESSURE = "backpressure"
ST_BREAKER_OPEN = "breaker_open"
ST_REPLICA_DEAD = "replica_dead"
ST_TIMEOUT = "timeout"
ST_INVALID = "invalid"
ST_UNAVAILABLE = "unavailable"

#: Every non-ok status a reply can carry (the wire-visible taxonomy).
ERROR_STATUSES = (
    ST_BACKPRESSURE, ST_BREAKER_OPEN, ST_REPLICA_DEAD,
    ST_TIMEOUT, ST_INVALID, ST_UNAVAILABLE,
)


def wire_error(exc: BaseException, mid=None) -> dict:
    """The reply frame for a failed request: the taxonomy mapped onto
    a status code plus the fields the client needs to rebuild the
    SAME exception type (retry hints survive the wire)."""
    out: dict = {"error": str(exc) or type(exc).__name__}
    if mid is not None:
        out["id"] = mid
    if isinstance(exc, CircuitBreakerOpen):  # before the parent class
        out["status"] = ST_BREAKER_OPEN
        out["kind"] = exc.kind
        out["retry_after_s"] = exc.retry_after_s
        out["tenant"] = exc.tenant
    elif isinstance(exc, BackpressureError):
        out["status"] = ST_BACKPRESSURE
        out["retry_after_s"] = exc.retry_after_s
        out["tenant"] = exc.tenant
    elif isinstance(exc, ReplicaDeadError):
        out["status"] = ST_REPLICA_DEAD
    elif isinstance(exc, (TimeoutError, IpcTimeoutError)):
        # IpcTimeoutError is deliberately NOT a TimeoutError subclass
        # (it must stay read-retryable inside the fleet); on the wire
        # both are the same fact — the deadline expired
        out["status"] = ST_TIMEOUT
    elif isinstance(exc, (ValueError, KeyError, TypeError)):
        out["status"] = ST_INVALID
    else:
        out["status"] = ST_UNAVAILABLE
    return out


def wire_exception(msg: dict) -> Exception:
    """Rebuild the typed exception a non-ok reply encodes (the client
    side of :func:`wire_error`); unknown statuses degrade to
    ``RuntimeError`` so a newer server cannot crash an older client."""
    status = msg.get("status")
    err = msg.get("error", status)
    retry = float(msg.get("retry_after_s") or 0.0)
    tenant = msg.get("tenant")
    if status == ST_BREAKER_OPEN:
        return CircuitBreakerOpen(
            msg.get("kind", "?"), retry, tenant=tenant
        )
    if status == ST_BACKPRESSURE:
        return BackpressureError(
            int(msg.get("depth") or 0), retry, tenant=tenant
        )
    if status == ST_REPLICA_DEAD:
        return ReplicaDeadError(err)
    if status == ST_TIMEOUT:
        return TimeoutError(err)
    if status == ST_INVALID:
        return ValueError(err)
    return RuntimeError(err)
