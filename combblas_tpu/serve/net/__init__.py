"""``combblas_tpu.serve.net`` — the TCP network front door (round 19).

Layer 9 of the serving stack (``serve/__init__.py`` has the map):
``frontend.py`` listens on a stdlib TCP socket and bridges the
versioned wire protocol (``protocol.py``, spoken over the shared
``serve/frame.py`` codec — one codec, two transports) to any
in-process backend (``Server``/``PoolServer``/``FleetRouter``/
``ProcessFleet``); ``client.py`` is the blocking client; and
``loadgen.py`` is the OPEN-LOOP Poisson load harness
(``BENCH_SERVE_NET=1``) — the coordinated-omission-free capstone
serving bench.  docs/serving.md "Network front door" has the
protocol frames, the status taxonomy table, and deadline semantics.
"""

from .client import NetClient
from .frontend import NetFrontend
from .protocol import (
    ERROR_STATUSES,
    PROTOCOL_VERSION,
    wire_error,
    wire_exception,
)

__all__ = [
    "NetClient",
    "NetFrontend",
    "PROTOCOL_VERSION",
    "ERROR_STATUSES",
    "wire_error",
    "wire_exception",
]
