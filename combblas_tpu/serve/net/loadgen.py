"""Open-loop load generator for the network front door (r19).

The closed-loop ``serve_bench`` scenarios submit, wait, submit again —
so when the server slows down, the BENCH slows its arrival rate with
it and the recorded latencies silently exclude the queueing the real
world would have seen (COORDINATED OMISSION).  This harness is the
open-loop antidote, and the capstone serving bench later PRs cite:

* arrivals are a SEEDED POISSON PROCESS at a target rate — the full
  schedule (exponential inter-arrival gaps, connection choice, root
  choice) is drawn up front from one ``numpy`` RNG, so a run replays
  exactly;
* send time is driven by the SCHEDULE, never by completions — the
  pacer thread sleeps to each arrival's offset and fires
  ``NetClient.submit_nowait`` regardless of what is still in flight;
* latency is measured from the SCHEDULED arrival time, so any send
  lag or server queueing is charged to the request, exactly as a real
  user would experience it;
* hundreds of concurrent connections against a 2+-replica
  ``ProcessFleet``, optionally under scripted ``ProcessFaultPlan``
  chaos (``BENCH_NET_CHAOS=1`` SIGKILLs a non-home replica mid-run
  with the supervisor healing around it).

Reported per run: offered vs achieved rate, p50/p99 latency,
availability, every rejection bucketed by its TYPED protocol status
(an untyped failure fails the gate), stranded-future and post-warmup
retrace counts (both must be zero), SLO burn when a deadline rides
the wire, and the stitched ``net -> router -> ipc -> child`` stage
decomposition folded from the same schema-``trace`` records the rest
of the observability plane uses.

Knobs (tuner/config.py): ``BENCH_NET_RATE`` (req/s),
``BENCH_NET_CONNS``, ``BENCH_NET_SECONDS``.  Entry:
``BENCH_SERVE_NET=1 python benchmarks/serve_bench.py`` (or
``python -m combblas_tpu.serve.net.loadgen``), emitting the standard
``{summary, metric, value, median, warning, rc}`` headline contract —
``warning`` is ``None`` here; the closed-loop scenarios are the ones
stamped ``"closed-loop (coordinated omission)"``.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time

import numpy as np

from ... import obs
from ...obs import trace as obs_trace
from ...tuner import config as tuner_config
from ..policy import ReplicaDeadError
from ..scheduler import BackpressureError, CircuitBreakerOpen
from .client import NetClient
from .frontend import NetFrontend

#: Trace stages charged to each tier of the stitched decomposition;
#: anything not listed is CHILD time (queue/assemble/execute/scatter —
#: the replica's own stage names, whatever they are).
_NET_STAGES = ("net_accept", "net_read", "net_write")
_ROUTER_STAGES = ("route", "ipc_recv")
_IPC_STAGES = ("ipc_send", "ipc_wait")


def _classify(exc: BaseException | None) -> str:
    """The harness-side status bucket for one settled future — the
    wire taxonomy's exception types, plus ``untyped:*`` for anything
    the protocol failed to map (which fails the gate)."""
    if exc is None:
        return "ok"
    if isinstance(exc, CircuitBreakerOpen):
        return "breaker_open"
    if isinstance(exc, BackpressureError):
        return "backpressure"
    if isinstance(exc, ReplicaDeadError):
        return "replica_dead"
    if isinstance(exc, TimeoutError):
        return "timeout"
    if isinstance(exc, ValueError):
        return "invalid"
    if isinstance(exc, ConnectionError):
        return "conn_error"
    if isinstance(exc, RuntimeError):
        return "unavailable"
    return f"untyped:{type(exc).__name__}"


def _decompose(records) -> dict:
    """Fold net-transport trace records into mean per-tier
    milliseconds (net/router/ipc/child + wall)."""
    tiers = {"net_ms": 0.0, "router_ms": 0.0, "ipc_ms": 0.0,
             "child_ms": 0.0}
    wall = 0.0
    n = 0
    for rec in records:
        if rec["labels"].get("transport") != "net":
            continue
        n += 1
        wall += rec["wall_s"]
        for st in rec["stages"]:
            s = st["stage"]
            if s in _NET_STAGES:
                tiers["net_ms"] += st["s"]
            elif s in _ROUTER_STAGES:
                tiers["router_ms"] += st["s"]
            elif s in _IPC_STAGES:
                tiers["ipc_ms"] += st["s"]
            else:
                tiers["child_ms"] += st["s"]
    if n == 0:
        return {"traced": 0}
    out = {k: round(v / n * 1e3, 4) for k, v in tiers.items()}
    out["wall_ms"] = round(wall / n * 1e3, 4)
    out["traced"] = n
    return out


def run(rate: float | None = None, conns: int | None = None,
        seconds: float | None = None, *, scale: int = 8,
        edgefactor: int = 8, replicas: int = 2, chaos: bool = False,
        seed: int = 7, kind: str = "bfs",
        deadline_s: float | None = 2.0, trace_rate: float = 1.0,
        backend=None) -> dict:
    """One open-loop run; returns the result dict (``main`` wraps it
    in the headline contract).  ``backend=None`` builds (and owns) a
    ``ProcessFleet``; passing a backend reuses it (tests)."""
    from ...utils.rmat import rmat_symmetric_coo_host

    rate = tuner_config.bench_net_rate(rate)
    conns = tuner_config.bench_net_conns(conns)
    seconds = tuner_config.bench_net_seconds(seconds)

    was_enabled = obs.ENABLED
    if not was_enabled:
        obs.enable(install_hooks=False)
    prev_rate = obs_trace.sample_rate()
    obs_trace.set_sample_rate(trace_rate)

    n = 1 << scale
    rows, cols = rmat_symmetric_coo_host(42, scale, edgefactor)
    deg = np.bincount(rows, minlength=n)
    roots = np.flatnonzero(deg > 0).astype(np.int64)

    own_fleet = backend is None
    work = None
    if own_fleet:
        from .. import ProcessFleet, ServeConfig

        work = tempfile.mkdtemp(prefix="net_loadgen_")
        backend = ProcessFleet.build(
            (1, 1), rows, cols, n, replicas=replicas, kinds=(kind,),
            config=ServeConfig(
                lane_widths=(1, 2, 4, 8), slo_deadline_s=deadline_s,
            ),
            wal_dir=os.path.join(work, "wal"),
            workdir=os.path.join(work, "proc"),
            hb_interval_s=0.2,
        )
        backend.start_supervisor(0.2)
    fe = NetFrontend(backend, max_conns=conns + 16)
    clients: list[NetClient] = []
    try:
        clients = [
            NetClient("127.0.0.1", fe.port) for _ in range(conns)
        ]
        # warmup: a few blocking requests round-robin so every lane
        # plan is traced before measurement starts, then snapshot the
        # retrace marks and the trace log length
        for i in range(8):
            clients[i % len(clients)].submit(
                kind, int(roots[i % len(roots)]), timeout_s=300.0
            )
        marks = (
            backend.trace_marks()
            if hasattr(backend, "trace_marks") else None
        )
        n_traces0 = len(obs_trace.records())

        if chaos and hasattr(backend, "proc_faults"):
            from .. import ProcessFaultPlan

            n_arr_est = max(int(rate * seconds), 1)
            plan = ProcessFaultPlan()
            # kill a non-home replica a third of the way in; the
            # supervisor heals it while the stream keeps flowing
            victim = (backend.home + 1) % len(backend.replicas)
            plan.sigkill(at=max(n_arr_est // 3, 1), replica=victim)
            backend.proc_faults = plan

        # the precomputed seeded schedule: everything random is drawn
        # here, before the clock starts
        rng = np.random.default_rng(seed)
        n_arr = max(int(rate * seconds), 1)
        offsets = np.cumsum(rng.exponential(1.0 / rate, n_arr))
        conn_of = rng.integers(0, len(clients), n_arr)
        root_of = roots[rng.integers(0, len(roots), n_arr)]

        recs: list = [None] * n_arr
        left = [n_arr]
        lk = threading.Lock()
        all_done = threading.Event()

        def _settle(k: int, sched_t: float, f) -> None:
            # latency from the SCHEDULED arrival: send lag and queue
            # wait are charged to the request — no coordinated omission
            lat = time.perf_counter() - sched_t
            recs[k] = (lat, _classify(f.exception()))
            with lk:
                left[0] -= 1
                if left[0] == 0:
                    all_done.set()

        t_start = time.perf_counter()
        send_lag_max = 0.0
        for k in range(n_arr):
            tgt = t_start + offsets[k]
            now = time.perf_counter()
            if tgt > now:
                time.sleep(tgt - now)
            else:
                send_lag_max = max(send_lag_max, now - tgt)
            try:
                fut = clients[conn_of[k]].submit_nowait(
                    kind, int(root_of[k]), deadline_s=deadline_s
                )
            except ConnectionError:
                recs[k] = (time.perf_counter() - tgt, "conn_error")
                with lk:
                    left[0] -= 1
                    if left[0] == 0:
                        all_done.set()
                continue
            fut.add_done_callback(
                lambda f, k=k, tgt=tgt: _settle(k, tgt, f)
            )
        sent_wall = time.perf_counter() - t_start
        all_done.wait(timeout=seconds + 120.0)
        total_wall = time.perf_counter() - t_start
        stranded = left[0]

        status_counts: dict[str, int] = {}
        lats_ok = []
        for r in recs:
            if r is None:
                continue
            lat, st = r
            status_counts[st] = status_counts.get(st, 0) + 1
            if st == "ok":
                lats_ok.append(lat)
        n_ok = len(lats_ok)
        availability = n_ok / n_arr
        lats_ms = np.asarray(lats_ok) * 1e3
        p50 = float(np.percentile(lats_ms, 50)) if n_ok else 0.0
        p99 = float(np.percentile(lats_ms, 99)) if n_ok else 0.0
        untyped = sum(
            v for k2, v in status_counts.items()
            if k2.startswith("untyped:")
        )
        retraces = (
            backend.retraces_since(marks) if marks is not None else 0
        )
        client_pending = sum(c.pending for c in clients)
        slo = None
        if deadline_s is not None and n_ok:
            miss = int(np.sum(lats_ms > deadline_s * 1e3))
            bad = miss + (n_arr - n_ok)
            slo = {
                "deadline_s": deadline_s,
                "bad": bad,
                "burn": round(bad / max(n_arr * 0.01, 1.0), 4),
                # burn vs a 99%-availability budget: >= 1.0 means the
                # run spent the whole 1% error budget
            }
        decomposition = _decompose(obs_trace.records()[n_traces0:])

        ok = (
            availability >= 0.99 and stranded == 0
            and client_pending == 0 and untyped == 0 and retraces == 0
        )
        return {
            "metric": "serve.net.open_loop",
            "unit": "req/s",
            "value": round(n_ok / total_wall, 2),
            "offered_qps": round(n_arr / offsets[-1], 2),
            "achieved_qps": round(n_ok / total_wall, 2),
            "requests": n_arr,
            "conns": len(clients),
            "replicas": (
                len(backend.replicas)
                if hasattr(backend, "replicas") else 1
            ),
            "seconds": seconds,
            "p50_ms": round(p50, 3),
            "p99_ms": round(p99, 3),
            "availability": round(availability, 5),
            "status_counts": status_counts,
            "untyped_failures": untyped,
            "stranded_futures": stranded + client_pending,
            "retraces_after_warmup": retraces,
            "send_lag_max_ms": round(send_lag_max * 1e3, 3),
            "sent_wall_s": round(sent_wall, 3),
            "wall_s": round(total_wall, 3),
            "chaos": bool(chaos),
            "slo": slo,
            "decomposition": decomposition,
            "warning": None,  # open loop: nothing to caveat
            "ok": ok,
        }
    finally:
        for c in clients:
            try:
                c.close()
            except Exception:
                pass
        fe.close()
        if own_fleet:
            backend.close(drain=False)
        obs_trace.set_sample_rate(prev_rate)


def main() -> int:
    """The ``BENCH_SERVE_NET=1`` entry: run, print the detail dict,
    then emit the headline ``{summary, metric, value, median,
    warning, rc}`` line + BENCH_SUMMARY.json (suppressed under
    bench.py's child runner via BENCH_EMIT_SUMMARY=0, where the
    detail line must stay last)."""
    chaos = os.environ.get("BENCH_NET_CHAOS", "0") not in ("", "0")
    scale = int(os.environ.get("BENCH_SERVE_SCALE", "8") or 8)
    replicas = int(os.environ.get("BENCH_NET_REPLICAS", "2") or 2)
    out = run(chaos=chaos, scale=scale, replicas=replicas)
    print(json.dumps(out), flush=True)
    if os.environ.get("BENCH_EMIT_SUMMARY", "1") == "0":
        return 0
    rc = 0 if out.get("ok") else 1
    s = {
        "summary": 1,
        "metric": out.get("metric"),
        "value": out.get("value", 0.0),
        "median": out.get("p50_ms", 0.0),
        "warning": out.get("warning"),
        "rc": rc,
        "offered_qps": out.get("offered_qps"),
        "achieved_qps": out.get("achieved_qps"),
        "availability": out.get("availability"),
        "decomposition": out.get("decomposition"),
    }
    path = os.environ.get("BENCH_SUMMARY_PATH", "BENCH_SUMMARY.json")
    try:
        with open(path, "w") as f:
            json.dump(s, f)
            f.write("\n")
    except OSError as e:
        s["summary_write_error"] = f"{path}: {e}"
    print(json.dumps(s), flush=True)
    return rc


if __name__ == "__main__":
    sys.exit(main())
