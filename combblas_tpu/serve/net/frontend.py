"""``NetFrontend`` — the TCP front door over any serving backend (r19).

A stdlib-socket listener (thread-per-connection accept loop — the
repo's no-asyncio style; connection counts here are hundreds, not
millions, and every blocking read has a poll-tick timeout) that speaks
``serve/net/protocol.py`` over the shared frame codec and fronts ANY
of the in-process backends:

* ``Server`` — one graph, one tenant;
* ``PoolServer`` — the hello frame's ``tenant`` header routes each
  connection to its tenant (unknown tenant: typed ``invalid`` reject);
* ``FleetRouter`` / ``ProcessFleet`` — replica routing, spillover and
  read-retry happen behind ``submit`` exactly as for local callers.

Requests are PIPELINED per connection and dispatched without waiting
for completions, so concurrent requests from one socket coalesce into
the scheduler's existing lane buckets like any other submit storm;
replies go out in completion order, correlated by ``id``.  A wire
``deadline_s`` becomes the scheduler's per-request timeout (still
CAPPED by ``ServeConfig.slo_deadline_s``).  Every taxonomy rejection
is a first-class wire reply — a connection is only ever closed by the
client, a torn frame, or ``close()``.

Tracing (round 19): the frontend rolls the deterministic sampler ONCE
at the socket, ``hold()``s the trace, charges ``net_accept`` (the
handshake, on the connection's first sampled request) and ``net_read``
(frame parse + validation), hands the SAME trace object down the
submit path (scheduler adoption via ``trace=``; process fleet via its
rid-stitching thread-local), and ``release()``s it after writing the
reply — so one schema-``trace`` record telescopes
``net_accept → net_read → [router/queue/execute stages] → net_write``
to the request's wall time.

Round-19 metric catalog (obs/metrics.py):
``serve.net.{connections,accept_queue,requests{op},bytes_in,
bytes_out,status{code},reply_drops}``.  ``/metrics``-equivalent health
rides the existing scrape plane: ``serve_metrics()`` attaches the
shared ``obs.export`` HTTP endpoint to this frontend (delegating to
the backend's federated records when it has them).
"""

from __future__ import annotations

import itertools
import socket
import threading
import time
from typing import Any

from ... import obs
from ...tuner import config as tuner_config
from ..frame import Channel, ChannelClosed
from . import protocol as P

#: Poll tick for per-connection reads: bounds how long close() and
#: disconnect detection can lag; partial frames survive ticks by
#: Channel's accumulator contract.
_POLL_S = 0.25

#: A client must complete its hello within this budget or the slot is
#: reclaimed (accept-queue hygiene; generous — one frame, not work).
_HELLO_TIMEOUT_S = 10.0


class _Conn:
    """One live connection's bookkeeping (owned by its reader thread;
    ``ch.send`` is thread-safe for the reply callbacks)."""

    __slots__ = ("cid", "ch", "tenant", "handshake_s", "traced")

    def __init__(self, cid: int, ch: Channel):
        self.cid = cid
        self.ch = ch
        self.tenant: str | None = None
        self.handshake_s = 0.0
        self.traced = False  # first sampled request charges net_accept


class NetFrontend:
    """TCP listener bridging wire frames to a serving backend.

    Knobs (tuner/config.py, argument > env > default):
    ``COMBBLAS_NET_PORT`` (0 = OS-assigned ephemeral, read back from
    :attr:`port`), ``COMBBLAS_NET_MAX_CONNS`` (connections past the
    cap get a typed ``backpressure`` hello-reply, then close),
    ``COMBBLAS_NET_ACCEPT_BACKLOG`` (``listen()`` queue).
    """

    def __init__(self, backend, *, host: str = "127.0.0.1",
                 port: int | None = None,
                 max_conns: int | None = None,
                 accept_backlog: int | None = None):
        self.backend = backend
        self._pooled = hasattr(backend, "pool")  # PoolServer duck type
        self.host = host
        self.max_conns = tuner_config.net_max_conns(max_conns)
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind((host, tuner_config.net_port(port)))
        self._lsock.listen(tuner_config.net_accept_backlog(accept_backlog))
        # poll-tick the accept loop: a blocking accept() is not
        # reliably woken by close() on another thread, and close()
        # must not stall behind its join
        self._lsock.settimeout(_POLL_S)
        self.port = self._lsock.getsockname()[1]
        self._lock = threading.Lock()
        self._conns: dict[int, _Conn] = {}
        self._threads: dict[int, threading.Thread] = {}
        self._cid = itertools.count(1)
        self._rid = itertools.count(1)  # trace rid namespace "net<n>"
        self._closing = False
        self._scrape = None
        self._hs = 0  # connections mid-handshake (accept_queue gauge)
        self.accepted = 0
        self.rejected_conns = 0
        self.requests = 0
        self.reply_drops = 0
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"combblas-net-accept:{self.port}",
        )
        self._accept_thread.start()

    # -- accept path -------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._closing:
            try:
                sock, _addr = self._lsock.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # listener closed by close()
            sock.settimeout(None)  # per-recv timeouts are Channel's job
            cid = next(self._cid)
            t = threading.Thread(
                target=self._serve_conn, args=(cid, sock),
                daemon=True, name=f"combblas-net-conn{cid}",
            )
            with self._lock:
                if self._closing:
                    try:
                        sock.close()
                    except OSError:
                        pass
                    return
                self._threads[cid] = t
            t.start()

    def _serve_conn(self, cid: int, sock: socket.socket) -> None:
        t_accept = time.perf_counter()
        obs.gauge("serve.net.accept_queue", self._in_handshake(+1))
        # a fixed peer class (not per-connection) keeps the shared
        # serve.ipc.* series cardinality bounded under conn churn
        ch = Channel(sock, peer="net")
        conn = _Conn(cid, ch)
        registered = False
        try:
            registered = self._handshake(conn, t_accept)
        finally:
            obs.gauge("serve.net.accept_queue", self._in_handshake(-1))
        if not registered:
            ch.close()
            with self._lock:
                self._threads.pop(cid, None)
            return
        try:
            self._conn_loop(conn)
        finally:
            ch.close()
            with self._lock:
                self._conns.pop(cid, None)
                self._threads.pop(cid, None)
                n = len(self._conns)
            obs.gauge("serve.net.connections", n)

    def _in_handshake(self, delta: int) -> int:
        with self._lock:
            cur = getattr(self, "_hs", 0) + delta
            self._hs = max(cur, 0)
            return self._hs

    def _handshake(self, conn: _Conn, t_accept: float) -> bool:
        """Read + answer the hello frame; every refusal is a typed
        wire reply (never a dropped connection).  Returns whether the
        connection was admitted and registered."""
        try:
            m = conn.ch.recv(timeout=_HELLO_TIMEOUT_S)
        except Exception:
            return False  # no (whole, well-formed) hello: nothing to
            # answer — covers timeout, disconnect, torn/corrupt frame
        obs.count("serve.net.bytes_in", conn.ch.bytes_in)
        mid = m.get("id") if isinstance(m, dict) else None
        if (not isinstance(m, dict)) or m.get("op") != "hello":
            self._try_send(conn, P.wire_error(
                ValueError("first frame must be the hello"), mid
            ))
            return False
        if m.get("v") != P.PROTOCOL_VERSION:
            self._try_send(conn, P.wire_error(ValueError(
                f"protocol version {m.get('v')!r} != "
                f"{P.PROTOCOL_VERSION}"
            ), mid))
            return False
        tenant = m.get("tenant")
        if self._pooled:
            if tenant is None:
                self._try_send(conn, P.wire_error(ValueError(
                    "tenant header required by a pooled backend"
                ), mid))
                return False
            if tenant not in self.backend.pool.tenant_names():
                self._try_send(conn, P.wire_error(
                    KeyError(f"unknown tenant {tenant!r}"), mid
                ))
                return False
        conn.tenant = tenant if isinstance(tenant, str) else None
        with self._lock:
            if self._closing:
                admitted = False
            else:
                admitted = len(self._conns) < self.max_conns
                if admitted:
                    self._conns[conn.cid] = conn
                    self.accepted += 1
                n = len(self._conns)
        if not admitted:
            self.rejected_conns += 1
            obs.count("serve.net.status", code=P.ST_BACKPRESSURE)
            self._try_send(conn, {
                "id": mid, "status": P.ST_BACKPRESSURE,
                "error": f"connection limit ({self.max_conns}) reached",
                "retry_after_s": 0.05,
            })
            return False
        obs.gauge("serve.net.connections", n)
        conn.handshake_s = time.perf_counter() - t_accept
        self._try_send(conn, {
            "id": mid, "status": P.ST_OK, "v": P.PROTOCOL_VERSION,
            "pooled": self._pooled,
        })
        return True

    # -- request path ------------------------------------------------------

    def _conn_loop(self, conn: _Conn) -> None:
        while not self._closing:
            b0 = conn.ch.bytes_in  # advances only on whole frames
            try:
                m = conn.ch.recv(timeout=_POLL_S)
            except socket.timeout:
                continue
            except Exception:
                # disconnect, torn frame, oversized prefix, or corrupt
                # JSON: the stream is unrecoverable — clean up.  Any
                # in-flight backend futures still settle server-side;
                # their reply callbacks hit the closed channel and are
                # counted as reply_drops, never stranded.
                return
            obs.count("serve.net.bytes_in", conn.ch.bytes_in - b0)
            if not isinstance(m, dict):
                self._send_reply(conn, P.wire_error(
                    ValueError("request frame must be a JSON object"),
                ))
                continue
            self._dispatch(conn, m)

    def _dispatch(self, conn: _Conn, m: dict) -> None:
        op = m.get("op")
        mid = m.get("id")
        self.requests += 1
        obs.count(
            "serve.net.requests",
            op=op if isinstance(op, str) else "?",
        )
        if op == "ping":
            self._send_reply(conn, {
                "id": mid, "status": P.ST_OK,
                "result": {"pong": True, "t": time.time()},
            })
        elif op == "submit":
            self._do_submit(conn, m)
        elif op == "submit_many":
            self._do_submit_many(conn, m)
        elif op == "submit_update":
            self._do_submit_update(conn, m)
        elif op == "stats":
            self._do_info(conn, mid, self.stats)
        elif op == "health":
            self._do_info(conn, mid, self.health)
        else:
            self._send_reply(conn, P.wire_error(
                ValueError(f"unknown op {op!r}"), mid
            ))

    def _deadline(self, m: dict) -> float | None:
        d = m.get("deadline_s")
        if d is None:
            return None
        t = float(d)
        if not (t > 0):
            raise ValueError(f"deadline_s must be > 0, got {d!r}")
        return t

    def _open_trace(self, conn: _Conn, kind):
        tr = obs.request_trace(
            f"net{next(self._rid)}",
            kind=kind if isinstance(kind, str) else None,
            tenant=conn.tenant,
        )
        if tr is None:
            return None
        # deferred commit: the scheduler/fleet will call finish() when
        # the request settles; we still owe the net_write tail
        tr.hold()
        tr.annotate(transport="net")
        if not conn.traced:
            # charge the TCP handshake to this connection's first
            # sampled request: widen the wall by handshake_s and book
            # the same amount as the leading stage, preserving
            # sum(stages) == wall_s exactly
            conn.traced = True
            tr.t0 -= conn.handshake_s
            tr.stages.append(["net_accept", conn.handshake_s])
        return tr

    def _do_submit(self, conn: _Conn, m: dict) -> None:
        mid = m.get("id")
        kind = m.get("kind")
        try:
            timeout_s = self._deadline(m)
        except (TypeError, ValueError) as e:
            self._send_reply(conn, P.wire_error(
                e if isinstance(e, ValueError) else ValueError(str(e)),
                mid,
            ))
            return
        tr = self._open_trace(conn, kind)
        if tr is not None:
            tr.mark("net_read")  # frame parse + validation
        try:
            fut = self._backend_submit(
                conn, kind, m.get("root"), timeout_s, tr
            )
        except Exception as e:
            # synchronous admission rejection (backpressure, breaker,
            # unknown kind/tenant, closing): a first-class wire reply
            self._send_reply(conn, P.wire_error(e, mid), trace=tr)
            return
        fut.add_done_callback(
            lambda f: self._reply_future(conn, mid, f, tr)
        )

    def _backend_submit(self, conn: _Conn, kind, root, timeout_s, tr):
        if self._pooled:
            return self.backend.submit(
                conn.tenant, kind, root, timeout_s=timeout_s, trace=tr
            )
        return self.backend.submit(
            kind, root, timeout_s=timeout_s, trace=tr
        )

    def _do_submit_many(self, conn: _Conn, m: dict) -> None:
        mid = m.get("id")
        kind = m.get("kind")
        roots = m.get("roots")
        try:
            timeout_s = self._deadline(m)
            if not isinstance(roots, list):
                raise ValueError("submit_many needs a roots list")
        except (TypeError, ValueError) as e:
            self._send_reply(conn, P.wire_error(
                e if isinstance(e, ValueError) else ValueError(str(e)),
                mid,
            ))
            return
        try:
            if self._pooled:
                futs = self.backend.submit_many(
                    conn.tenant, kind, roots, timeout_s=timeout_s
                )
            else:
                futs = self.backend.submit_many(
                    kind, roots, timeout_s=timeout_s
                )
        except Exception as e:
            self._send_reply(conn, P.wire_error(e, mid))
            return
        if not futs:
            self._send_reply(
                conn, {"id": mid, "status": P.ST_OK, "results": []}
            )
            return
        # one reply frame once every per-root future settles; entries
        # carry their own status (prefix-rejection semantics survive
        # the wire as typed per-root entries, not a torn batch)
        results: list[Any] = [None] * len(futs)
        left = [len(futs)]
        lk = threading.Lock()

        def _on_done(j, f):
            exc = f.exception()
            if exc is None:
                results[j] = {"status": P.ST_OK, "result": f.result()}
            else:
                results[j] = P.wire_error(exc)
            with lk:
                left[0] -= 1
                done = left[0] == 0
            if done:
                self._send_reply(conn, {
                    "id": mid, "status": P.ST_OK, "results": results,
                })

        for j, f in enumerate(futs):
            f.add_done_callback(
                lambda f, j=j: _on_done(j, f)
            )

    def _do_submit_update(self, conn: _Conn, m: dict) -> None:
        mid = m.get("id")
        ops = m.get("ops")
        if not isinstance(ops, list):
            self._send_reply(conn, P.wire_error(
                ValueError("submit_update needs an ops list"), mid
            ))
            return
        try:
            ops_t = [tuple(o) for o in ops]
            if self._pooled:
                fut = self.backend.submit_update(conn.tenant, ops_t)
            else:
                fut = self.backend.submit_update(ops_t)
        except Exception as e:
            self._send_reply(conn, P.wire_error(e, mid))
            return
        fut.add_done_callback(
            lambda f: self._reply_future(conn, mid, f, None)
        )

    def _do_info(self, conn: _Conn, mid, fn) -> None:
        try:
            self._send_reply(conn, {
                "id": mid, "status": P.ST_OK, "result": fn(),
            })
        except Exception as e:
            self._send_reply(conn, P.wire_error(e, mid))

    # -- reply path --------------------------------------------------------

    def _reply_future(self, conn: _Conn, mid, fut, tr) -> None:
        exc = fut.exception()
        if exc is None:
            msg = {"id": mid, "status": P.ST_OK, "result": fut.result()}
        else:
            msg = P.wire_error(exc, mid)
        self._send_reply(conn, msg, trace=tr)

    def _send_reply(self, conn: _Conn, msg: dict, trace=None) -> None:
        code = msg.get("status", P.ST_UNAVAILABLE)
        obs.count("serve.net.status", code=code)
        try:
            n = conn.ch.send(msg)
            obs.count("serve.net.bytes_out", n)
        except ValueError:
            # reply overflowed MAX_FRAME: degrade to a typed error so
            # the request id still settles client-side
            self._try_send(conn, P.wire_error(
                RuntimeError("reply exceeds frame limit"), msg.get("id")
            ))
        except ChannelClosed:
            # client disconnected before its reply: the backend future
            # settled regardless — dropped reply, not a stranded future
            self.reply_drops += 1
            obs.count("serve.net.reply_drops")
        if trace is not None:
            trace.release(status=code, stage="net_write")

    def _try_send(self, conn: _Conn, msg: dict) -> None:
        try:
            n = conn.ch.send(msg)
            obs.count("serve.net.bytes_out", n)
        except (ChannelClosed, ValueError):
            self.reply_drops += 1
            obs.count("serve.net.reply_drops")

    # -- observability / lifecycle ----------------------------------------

    def stats(self) -> dict:
        with self._lock:
            conns = len(self._conns)
        net = {
            "port": self.port,
            "connections": conns,
            "accepted": self.accepted,
            "rejected_conns": self.rejected_conns,
            "requests": self.requests,
            "reply_drops": self.reply_drops,
            "max_conns": self.max_conns,
        }
        return {"net": net, "backend": self.backend.stats()}

    def health(self) -> dict:
        h = self.backend.health()
        return {
            "status": h.get("status", "ok"),
            "net": {
                "port": self.port,
                "connections": len(self._conns),
                "closing": self._closing,
            },
            "backend": h,
        }

    def metrics_records(self) -> list[dict]:
        """The scrape body: the backend's federated records when it
        has them (ProcessFleet replica metrics), the process-global
        registry otherwise — serve.net.* counters live there either
        way."""
        fn = getattr(self.backend, "metrics_records", None)
        if fn is not None:
            return fn()
        return obs.metrics_snapshot()

    def serve_metrics(self, port: int = 0, host: str = "127.0.0.1"
                      ) -> int:
        """Attach the shared /metrics //healthz //statz scrape plane
        to this frontend; returns the bound port."""
        from ...obs import export

        return export.attach_scrape(self, port=port, host=host)

    def close(self) -> None:
        """Stop accepting, close every connection, detach the scrape.
        The BACKEND is not closed — its owner decides."""
        self._closing = True
        try:
            self._lsock.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns.values())
            threads = list(self._threads.values())
        for c in conns:
            c.ch.close()
        self._accept_thread.join(timeout=5.0)
        for t in threads:
            t.join(timeout=5.0)
        if self._scrape is not None:
            from ...obs import export

            export.detach_scrape(self)

    def __enter__(self) -> "NetFrontend":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
