"""ProcessFleet — replica servers as real OS subprocesses (round 17).

The thread-hosted ``FleetRouter`` is the one-host analog of a replica
fleet: its "crash" is a worker-thread death inside one address space,
and — because two threads launching collective SPMD programs on one
mesh deadlock XLA's all-reduce rendezvous (PR 12) — all of its
replicas serialize on ONE shared exec lock.  This module is the real
thing on one machine: each replica is a subprocess hosting a
``Server`` with its OWN JAX runtime (``serve/_procworker.py``; the
parent exports per-child ``JAX_PLATFORMS``/``XLA_FLAGS``), so

* replica death is PROCESS death (``SIGKILL`` kills a real crash
  domain: heap, device buffers, locks, threads — nothing to clean up,
  nothing half-poisoned survives),
* a wedged replica (``SIGSTOP``, a runaway GC, a stuck syscall) hangs
  only ITSELF: the router's per-request IPC deadlines fail its
  in-flight futures and the heartbeat timeout routes around it, and
* replicas execute in PARALLEL — N processes, N meshes, no shared
  lock: the first honest replica-parallelism measurement
  (``BENCH_FLEET=process``).

What is SHARED is exactly what PR 14 built process-safe: the plan
store (children inherit ``COMBBLAS_PLAN_STORE`` and warm from it —
zero post-warmup retraces, asserted over IPC), the WAL + checkpoint
durability dir (the HOME child owns the log; promotion and respawn
recover from the files), and the spool dir graph versions travel
through as ``save_version`` checkpoints (``swap_from_checkpoint`` —
never pickled device arrays over a pipe).

Routing, spillover, bounded read retry, and the supervision loop come
from ``serve/policy.py`` — the same policy the thread fleet runs,
with process-level liveness plugged into its hooks: ``Popen.poll()``
and broken-pipe detection catch crashes, heartbeat age catches hangs,
quarantine fails in-flight futures honestly (``ReplicaDeadError``),
replacements respawn warm from checkpoint+WAL, a dead HOME promotes a
survivor at the WAL frontier over IPC, and repeated respawn failures
degrade to capped-backoff retry on the survivors — never a router
crash.  ``serve/faults.py``'s ``ProcessFaultPlan`` scripts real
``SIGKILL``/``SIGSTOP`` chaos deterministically.
"""

from __future__ import annotations

import dataclasses
import itertools
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor

from .. import obs
from ..obs.fleetlog import FleetLog
from ..obs.recorder import FlightRecorder
from ..tuner import config as tuner_config
from .batcher import settle
from .faults import ProcessFaultPlan
from .ipc import Channel, ChannelClosed
from .policy import ReplicaDeadError, ReplicaFleetBase, StaleEpochError
from .scheduler import BackpressureError, ServeConfig

#: Router-thread handoff for cross-process trace stitching (round 18):
#: ``ProcessFleet.submit`` parks the stitched trace here, the replica
#: handle it routes to picks it up and stamps its rid into the IPC
#: frame.  Thread-local because concurrent submitting threads must not
#: cross their traces; read-retry resubmits (which run on reader
#: threads, where this is empty) are deliberately untraced — the
#: stitched trace covers the FIRST attempt, the retry is visible as
#: the ``read_retry`` counter.
_stitch = threading.local()

__all__ = ["ProcessFleet", "ReplicaProc", "IpcTimeoutError",
           "ReplicaDeadError"]


class IpcTimeoutError(RuntimeError):
    """A replica did not answer an IPC request within its deadline —
    the replica-level failure of a HUNG (not just dead) process.
    Deliberately a ``RuntimeError``, not a ``TimeoutError``: the
    router's read-retry taxonomy re-submits replica-level failures to
    the next-best replica, and a wedged replica's reads should fail
    over, not surface as a caller-deadline lie."""


#: Child-error name -> parent exception class (the retry/spillover
#: taxonomy must survive the wire: BackpressureError spills,
#: ValueError/TimeoutError do NOT read-retry, StaleEpochError replays
#: the sharded batch WITHOUT quarantining the slice, anything else
#: does retry).
_EXC_TYPES = {
    "BackpressureError": BackpressureError,
    "ValueError": ValueError,
    "TimeoutError": TimeoutError,
    "StaleEpochError": StaleEpochError,
}


def _rebuild_exc(msg: dict) -> Exception:
    etype = msg.get("etype", "RuntimeError")
    text = f"[replica {etype}] {msg.get('error', '')}"
    if etype == "BackpressureError":
        e = BackpressureError(
            0, float(msg.get("retry_after_s") or 0.01)
        )
        e.args = (text,)
        return e
    cls = _EXC_TYPES.get(etype, RuntimeError)
    return cls(text)


class _Rpc:
    __slots__ = ("future", "deadline", "t0", "op", "trace")

    def __init__(self, future, deadline, t0, op, trace=None):
        self.future = future
        self.deadline = deadline
        self.t0 = t0
        self.op = op
        self.trace = trace


class ReplicaProc:
    """Parent-side handle for one replica subprocess: the Popen, the
    framed channel, the reader thread that settles RPC futures and
    tracks heartbeats, and the per-request deadline sweep that turns
    a hung replica into failed futures instead of a wedged router."""

    def __init__(self, idx: int, proc, channel: Channel, *,
                 tenant: str | None = None,
                 max_inflight: int = 256,
                 ipc_timeout_s: float = 60.0):
        self.idx = idx
        self.proc = proc  # Popen-like (poll/pid/send_signal) or None
        self.ch = channel
        self.tenant = tenant or f"proc{idx}"
        self.max_inflight = int(max_inflight)
        self.ipc_timeout_s = float(ipc_timeout_s)
        self._lock = threading.Lock()
        self._pending: dict[int, _Rpc] = {}
        self._next_id = 0
        self.quarantined = False
        self.broken = False
        self.admitted_t = time.monotonic()
        self.last_hb_t: float | None = None
        self.last_hb: dict = {}
        self.rpcs = 0
        self.ipc_timeouts = 0
        # federation: the child's last piggybacked registry snapshot
        # (the aggregate() wire shape), folded into the fleet scrape
        # with a replica= label by ProcessFleet.metrics_records()
        self.last_metrics: list | None = None
        self.last_metrics_t: float | None = None
        self._reader = threading.Thread(
            target=self._read_loop,
            name=f"combblas-proc-rx{idx}", daemon=True,
        )
        self._reader.start()

    # -- the RPC surface ---------------------------------------------------

    def rpc(self, op: str, payload: dict | None = None,
            timeout_s: float | None = None, trace=None) -> Future:
        """Send one request; the returned future settles from the
        reader thread (reply, error, deadline, or channel death).
        ``trace`` is a router-side stitched RequestTrace: its
        ``route``/``ipc_send`` marks are charged here, and the reader
        thread stitches the child's stage marks into it on reply."""
        fut: Future = Future()
        deadline = time.monotonic() + (
            timeout_s if timeout_s is not None else self.ipc_timeout_s
        )
        with self._lock:
            if self.quarantined or self.broken:
                raise ReplicaDeadError(
                    f"replica {self.idx} is out of service"
                )
            rid = self._next_id
            self._next_id += 1
            self._pending[rid] = _Rpc(
                fut, deadline, time.perf_counter(), op, trace
            )
            self.rpcs += 1
        if trace is not None:
            # everything since submit-entry (fault step, route order,
            # admission checks) is routing time
            trace.mark("route")
        msg = {"id": rid, "op": op}
        if payload:
            msg.update(payload)
        try:
            self.ch.send(msg)
        except ChannelClosed as e:
            with self._lock:
                self._pending.pop(rid, None)
                self.broken = True
            raise ReplicaDeadError(
                f"replica {self.idx} channel broken: {e}"
            ) from e
        if trace is not None:
            trace.mark("ipc_send")
        return fut

    def call(self, op: str, payload: dict | None = None,
             timeout_s: float | None = None):
        """Synchronous RPC (construction / supervision paths)."""
        t = timeout_s if timeout_s is not None else self.ipc_timeout_s
        return self.rpc(op, payload, timeout_s=t).result(timeout=t + 5)

    def submit(self, kind: str, root, timeout_s: float | None = None
               ) -> Future:
        """The router-facing read/query surface.  Admission control is
        LOCAL (in-flight RPC bound mirroring the child's queue bound):
        a synchronous ``BackpressureError`` here is what lets the
        router's spillover loop try the next replica without paying a
        round trip; child-side rejections still arrive as failed
        futures and are not read-retried."""
        with self._lock:
            pending = len(self._pending)
        if pending >= self.max_inflight:
            raise BackpressureError(pending, 0.01, tenant=self.tenant)
        ipc_deadline = (
            (timeout_s + self.ipc_timeout_s)
            if timeout_s is not None else self.ipc_timeout_s
        )
        payload = {"kind": kind, "root": int(root)}
        if timeout_s is not None:
            payload["timeout_s"] = float(timeout_s)
        # stitched-trace handoff (module docstring): stamp the router's
        # rid + sampling decision into the frame header; cleared only
        # AFTER a successful send so a spillover to the next replica
        # keeps tracing the same request
        tr = getattr(_stitch, "trace", None)
        if tr is not None:
            payload["trace"] = tr.rid
        fut = self.rpc("submit", payload, timeout_s=ipc_deadline,
                       trace=tr)
        if tr is not None:
            _stitch.trace = None
        return fut

    # -- liveness ----------------------------------------------------------

    def depth(self) -> int:
        """Routing-time load: in-flight RPCs plus the child's last
        reported queue depth (the heartbeat's view of work the parent
        already handed over)."""
        with self._lock:
            d = len(self._pending)
        return d + int(self.last_hb.get("depth", 0))

    def is_serving(self) -> bool:
        if self.quarantined or self.broken:
            return False
        if self.proc is not None and self.proc.poll() is not None:
            return False  # exited: crash domain collapsed
        return True

    def heartbeat_age(self) -> float:
        """Seconds since the last heartbeat (or since admission when
        none arrived yet) — the hang detector's clock."""
        base = self.last_hb_t if self.last_hb_t is not None \
            else self.admitted_t
        return max(0.0, time.monotonic() - base)

    # -- reader / sweeper --------------------------------------------------

    def _read_loop(self) -> None:
        while True:
            try:
                m = self.ch.recv(timeout=0.1)
            except socket.timeout:
                self._sweep_deadlines()
                continue
            except Exception as e:
                # ChannelClosed — or a frame that would not decode (a
                # corrupted peer IS a broken peer): either way the
                # replica is out, its futures fail honestly, and the
                # reader must never die unhandled
                with self._lock:
                    self.broken = True
                self.fail_pending(ReplicaDeadError(
                    f"replica {self.idx} channel closed (process "
                    f"died, was killed, or sent garbage: "
                    f"{type(e).__name__})"
                ))
                return
            if "hb" in m:
                hb = m["hb"]
                snap = hb.pop("metrics", None)
                if snap is not None:
                    self.last_metrics = snap
                    self.last_metrics_t = time.monotonic()
                self.last_hb = hb
                self.last_hb_t = time.monotonic()
                continue
            with self._lock:
                rpc = self._pending.pop(m.get("id"), None)
            if rpc is None:
                continue  # deadline-failed earlier; late reply dropped
            obs.observe(
                "serve.procfleet.rpc_latency_s",
                time.perf_counter() - rpc.t0, op=rpc.op,
            )
            if rpc.trace is not None:
                # stitch + commit BEFORE the future settles: a caller
                # woken by result() must find its trace already in the
                # log (the round-15 attach-before-poppable precedent)
                self._stitch_reply(rpc.trace, m)
            if m.get("ok"):
                settle(rpc.future, result=m.get("result"))
            else:
                settle(rpc.future, exc=_rebuild_exc(m))
            self._sweep_deadlines()

    def _stitch_reply(self, trace, m: dict) -> None:
        """Fold the child's shipped stage marks into the router-side
        trace as ONE stitched record: ``route`` + ``ipc_send`` (marked
        at send), then the window since ``ipc_send`` split into
        ``ipc_wait`` (router-observed wait not accounted by the child)
        + the child's own queue_wait/assemble/execute/scatter marks,
        closed by ``ipc_recv`` — so ``sum(stages) == wall_s`` holds
        across two processes.  The two clocks never compare absolute
        values: the child contributes DURATIONS, scaled down if its
        reported total somehow exceeds the router-observed window
        (clock skew must not break the telescoping invariant)."""
        now = time.perf_counter()
        cw = max(now - trace._last, 0.0)
        child = m.get("trace")
        stages = (child or {}).get("stages") or []
        dt = sum(max(float(s["s"]), 0.0) for s in stages)
        scale = 1.0 if dt <= cw or dt <= 0.0 else cw / dt
        trace.stages.append(["ipc_wait", max(cw - dt * scale, 0.0)])
        for s in stages:
            trace.stages.append(
                [str(s["stage"]), max(float(s["s"]), 0.0) * scale]
            )
        trace._last = now
        trace.annotate(replica=self.idx)
        trace.finish(
            status="ok" if m.get("ok") else "error", stage="ipc_recv"
        )

    def _sweep_deadlines(self) -> None:
        now = time.monotonic()
        expired = []
        with self._lock:
            for rid, rpc in list(self._pending.items()):
                if now >= rpc.deadline:
                    expired.append(rpc)
                    del self._pending[rid]
        for rpc in expired:
            self.ipc_timeouts += 1
            obs.count("serve.procfleet.ipc_timeouts", op=rpc.op)
            obs.count("serve.ipc.deadline_missed", replica=self.idx)
            if rpc.trace is not None:
                rpc.trace.annotate(replica=self.idx)
                rpc.trace.finish(status="timeout", stage="ipc_wait")
            settle(rpc.future, exc=IpcTimeoutError(
                f"replica {self.idx} did not answer {rpc.op!r} "
                f"within its IPC deadline (hung or overloaded)"
            ))

    def fail_pending(self, exc: Exception) -> int:
        with self._lock:
            pending = list(self._pending.values())
            self._pending.clear()
        for rpc in pending:
            if rpc.trace is not None:
                rpc.trace.annotate(replica=self.idx)
                rpc.trace.finish(status="error", stage="ipc_wait")
            settle(rpc.future, exc=exc)
        return len(pending)

    # -- lifecycle ---------------------------------------------------------

    def signal(self, sig: int) -> None:
        if self.proc is not None and self.proc.poll() is None:
            self.proc.send_signal(sig)

    def quarantine(self, exc: Exception) -> int:
        """Take a dead/hung replica out of service: refuse new RPCs,
        fail every in-flight future honestly, SIGKILL the process
        (works on a SIGSTOPped one too — a wedged crash domain is
        collapsed, not negotiated with) and close the channel."""
        with self._lock:
            if self.quarantined:
                return 0
            self.quarantined = True
        n = self.fail_pending(exc)
        try:
            self.signal(signal.SIGKILL)
        except OSError:
            pass
        if self.proc is not None:
            try:
                self.proc.wait(timeout=10)
            except Exception:
                pass
        self.ch.close()
        obs.count("serve.procfleet.quarantined", replica=self.idx)
        return n

    def close(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Graceful shutdown: ask the child to drain and exit; fall
        back to SIGKILL when it cannot answer."""
        try:
            self.call("close", {"drain": drain, "timeout": timeout},
                      timeout_s=timeout)
        except Exception:
            pass  # dead/hung child: collapse it below
        if self.proc is not None:
            try:
                self.proc.wait(timeout=timeout)
            except Exception:
                try:
                    self.signal(signal.SIGKILL)
                    self.proc.wait(timeout=10)
                except Exception:
                    pass
        self.ch.close()
        self.fail_pending(RuntimeError(
            f"replica {self.idx} closed"
        ))


class ProcessFleet(ReplicaFleetBase):
    """Front door over N subprocess replicas (module docstring)."""

    _OBS = "serve.procfleet"

    def __init__(self, *, grid_shape, kinds, config: ServeConfig,
                 wal_dir: str, workdir: str, boot_ckpt: str,
                 devices: int | None = None,
                 hb_interval_s: float = 0.25,
                 hb_timeout_s: float = 5.0,
                 ipc_timeout_s: float = 60.0,
                 boot_timeout_s: float = 300.0,
                 respawn_backoff_s: float = 0.5,
                 respawn_backoff_max_s: float = 30.0,
                 home: int = 0,
                 metrics_interval_s: float | None = None,
                 fleetlog: str | None = None):
        self.grid_shape = tuple(grid_shape)
        self.kinds = tuple(kinds) if kinds else None
        self.config = config
        self.wal_dir = os.path.abspath(wal_dir)
        self.workdir = os.path.abspath(workdir)
        self.spool_dir = os.path.join(self.workdir, "spool")
        os.makedirs(self.spool_dir, exist_ok=True)
        self.boot_ckpt = boot_ckpt
        pr, pc = self.grid_shape
        self.devices = int(devices) if devices else max(pr * pc, 1)
        self.hb_interval_s = float(hb_interval_s)
        self.hb_timeout_s = float(hb_timeout_s)
        self.ipc_timeout_s = float(ipc_timeout_s)
        self.boot_timeout_s = float(boot_timeout_s)
        self.home = home
        #: Deterministic process-level chaos (SIGKILL/SIGSTOP rules),
        #: polled once per routed submit.
        self.proc_faults = ProcessFaultPlan()
        self.sigkills = 0
        self.sigstops = 0
        self.respawn_failures = 0
        self._respawn_base_s = float(respawn_backoff_s)
        self._respawn_cap_s = float(respawn_backoff_max_s)
        self._respawn_backoff: dict[int, float] = {}
        self._respawn_next: dict[int, float] = {}
        self._fan_lock = threading.Lock()
        # fan-out runs OFF the reader threads: a merge reply callback
        # that blocked on further RPCs to the same replica would
        # deadlock its own reader
        self._fan_pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="combblas-procfan"
        )
        self._closing = False
        self.replicas: list[ReplicaProc] = []
        # -- the fleet observability plane (round 18) ----------------------
        #: heartbeat-snapshot cadence the children piggyback registry
        #: snapshots at (knob: COMBBLAS_OBS_HB_METRICS_S)
        self.metrics_interval_s = tuner_config.obs_hb_metrics_interval(
            metrics_interval_s
        )
        #: supervision timeline — constructed EAGERLY (event() from
        #: supervisor/reader threads must never race a lazy init); the
        #: file itself appears only on the first event, and events are
        #: only emitted when obs is enabled (_fleet_event's gate)
        self.fleetlog = FleetLog(
            tuner_config.fleetlog_path(fleetlog)
            or os.path.join(self.workdir, "fleetlog.jsonl"),
            tenant="procfleet",
        )
        #: post-mortem ring, dumped on every quarantine/promotion
        self.recorder = FlightRecorder(
            out_dir=os.path.join(self.workdir, "flightrec"),
            tenant="procfleet",
        )
        #: stitched-trace rid source: crosses the IPC boundary in the
        #: frame header, so child and router halves correlate
        self._trace_rid = itertools.count(1)
        self._scrape = None  # serve_metrics() parity with Server

    # -- construction ------------------------------------------------------

    @staticmethod
    def build(grid_shape, rows, cols, nrows: int, *,
              replicas: int = 2, kinds=("bfs",),
              config: ServeConfig | None = None,
              wal_dir: str, workdir: str | None = None,
              home: int = 0, from_coo_kw: dict | None = None,
              **fleet_kw) -> "ProcessFleet":
        """Build the boot checkpoint from one COO on the PARENT's
        runtime (the only device work the router ever does), then
        spawn ``replicas`` children from it.  ``wal_dir`` is required:
        a process fleet's whole point is that replicas die for real,
        and respawn/promotion recover from checkpoint+WAL."""
        from .engine import GraphEngine
        from ..parallel.grid import Grid
        from ..utils import checkpoint

        if wal_dir is None:
            raise ValueError(
                "ProcessFleet requires a durability dir (wal_dir=): "
                "process replicas die for real, and respawn/promotion "
                "recover from checkpoint+WAL"
            )
        workdir = workdir or os.path.join(
            os.path.abspath(wal_dir), os.pardir, "procfleet"
        )
        os.makedirs(workdir, exist_ok=True)
        grid = Grid.make(*grid_shape)
        eng = GraphEngine.from_coo(
            grid, rows, cols, nrows, kinds=kinds, keep_coo=True,
            **(from_coo_kw or {}),
        )
        boot_ckpt = os.path.join(workdir, "boot.npz")
        checkpoint.save_version(boot_ckpt, eng.version)
        fleet = ProcessFleet(
            grid_shape=grid_shape, kinds=kinds,
            config=config or ServeConfig(),
            wal_dir=wal_dir, workdir=workdir, boot_ckpt=boot_ckpt,
            home=home, **fleet_kw,
        )
        fleet._boot_all(replicas)
        return fleet

    @staticmethod
    def from_checkpoint(path: str, grid_shape, *,
                        replicas: int = 2, kinds=("bfs",),
                        config: ServeConfig | None = None,
                        wal_dir: str, workdir: str | None = None,
                        home: int = 0, **fleet_kw) -> "ProcessFleet":
        """Spawn the fleet from a pre-staged ``save_version``
        checkpoint — the parent never builds a graph at all (the
        tier-1 test path, and the production ship-a-snapshot path)."""
        if wal_dir is None:
            raise ValueError("ProcessFleet requires wal_dir=")
        workdir = workdir or os.path.join(
            os.path.abspath(wal_dir), os.pardir, "procfleet"
        )
        os.makedirs(workdir, exist_ok=True)
        fleet = ProcessFleet(
            grid_shape=grid_shape, kinds=kinds,
            config=config or ServeConfig(),
            wal_dir=wal_dir, workdir=workdir, boot_ckpt=path,
            home=home, **fleet_kw,
        )
        fleet._boot_all(replicas)
        return fleet

    def _boot_all(self, n: int) -> None:
        if not (0 <= self.home < n):
            raise ValueError(f"home {self.home} outside [0, {n})")
        try:
            # launch every child FIRST, then collect the boot replies:
            # the expensive parts (JAX import, runtime init, checkpoint
            # load, warmup) run concurrently across the replicas
            # instead of paying N serial boots
            self.replicas = [self._launch(i) for i in range(n)]
            futs = [
                rp.rpc(
                    "boot",
                    self._boot_msg(i, recover=False,
                                   home=(i == self.home)),
                    timeout_s=self.boot_timeout_s,
                )
                for i, rp in enumerate(self.replicas)
            ]
            for rp, f in zip(self.replicas, futs):
                boot = f.result(timeout=self.boot_timeout_s + 5)
                self._admit_boot(rp, boot)
        except Exception:
            # a failed boot must not leak the siblings already spawned
            for rp in self.replicas:
                rp.quarantine(ReplicaDeadError("fleet boot failed"))
            self._fan_pool.shutdown(wait=False)
            raise
        self._init_policy()
        obs.gauge("serve.procfleet.replicas", len(self.replicas))

    def _child_env(self) -> dict:
        env = dict(os.environ)
        # the child's OWN runtime: its own cpu client, its own virtual
        # device partition — and hermetic durability (only the boot
        # message's wal_dir attaches a log, never ambient env)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count={self.devices}"
        )
        env["COMBBLAS_WAL"] = "0"
        # the child's telemetry arms with the ROUTER's current state,
        # not whatever COMBBLAS_OBS the operator's shell had: a fleet
        # whose parent enabled obs at runtime still federates
        env["COMBBLAS_OBS"] = "1" if obs.ENABLED else "0"
        # the child must import THIS package wherever the parent found
        # it — a parent that path-hacked sys.path (or runs from another
        # cwd) would otherwise spawn children that die on import
        import combblas_tpu

        pkg_root = os.path.dirname(os.path.dirname(
            os.path.abspath(combblas_tpu.__file__)
        ))
        pp = env.get("PYTHONPATH")
        env["PYTHONPATH"] = (
            pkg_root if not pp else pkg_root + os.pathsep + pp
        )
        return env

    def _launch(self, i: int) -> ReplicaProc:
        """Fork one replica child (socketpair + Popen) — cheap; the
        expensive initialization happens when its ``boot`` RPC runs."""
        parent_sock, child_sock = socket.socketpair()
        log = open(
            os.path.join(self.workdir, f"replica{i}.log"), "ab"
        )
        try:
            proc = subprocess.Popen(
                [
                    sys.executable, "-m",
                    "combblas_tpu.serve._procworker",
                    "--fd", str(child_sock.fileno()),
                ],
                pass_fds=(child_sock.fileno(),),
                env=self._child_env(),
                stdout=log, stderr=subprocess.STDOUT,
                start_new_session=True,  # chaos signals hit the
                # replica, never the router's process group
            )
        finally:
            log.close()
            child_sock.close()
        self._fleet_event("spawn", replica=i, pid=proc.pid)
        return ReplicaProc(
            i, proc, Channel(parent_sock, peer=f"replica{i}"),
            tenant=f"proc{i}",
            max_inflight=self.config.max_queue,
            ipc_timeout_s=self.ipc_timeout_s,
        )

    def _boot_msg(self, i: int, recover: bool, home: bool) -> dict:
        return {
            "grid": list(self.grid_shape),
            "ckpt": self.boot_ckpt,
            "kinds": list(self.kinds) if self.kinds else None,
            "config": dataclasses.asdict(self.config),
            "home": home,
            "wal_dir": self.wal_dir,
            "recover": recover,
            "tenant": f"proc{i}",
            "hb_interval_s": self.hb_interval_s,
            "metrics_interval_s": self.metrics_interval_s,
        }

    @staticmethod
    def _admit_boot(rp: ReplicaProc, boot: dict) -> None:
        rp.last_hb = {"depth": 0, "serving": True,
                      "pid": boot.get("pid")}
        rp.last_hb_t = time.monotonic()

    def _spawn(self, i: int, recover: bool, home: bool) -> ReplicaProc:
        """Fork + synchronously boot one replica (the respawn path —
        load checkpoint / recover, start server, warm from the shared
        plan store): the replica is serving when this returns."""
        rp = self._launch(i)
        try:
            boot = rp.call(
                "boot", self._boot_msg(i, recover, home),
                timeout_s=self.boot_timeout_s,
            )
        except Exception:
            rp.quarantine(ReplicaDeadError(
                f"replica {i} failed to boot"
            ))
            raise
        self._admit_boot(rp, boot)
        return rp

    # -- read path: the shared policy + scripted process chaos -------------

    def submit(self, kind: str, root, timeout_s: float | None = None,
               read_retry: int = 1, trace=None):
        for signame, rep in self.proc_faults.step():
            self._apply_fault(signame, rep)
        # cross-process trace stitching: one deterministic sampling
        # decision at the FRONT DOOR (obs.request_trace gates on
        # ENABLED + sample rate), handed to the routed replica via
        # thread-local; the child traces unconditionally under this
        # rid, so both halves of the stitched record correlate.
        # Round 19: when the NET frontend already opened (and holds) a
        # trace at the socket, adopt it — the sampler rolled once at
        # the outermost door, and the child's marks stitch into the
        # same record that carries net_accept/net_read/net_write.
        tr = (
            trace if trace is not None
            else obs.request_trace(next(self._trace_rid), kind=kind)
        )
        if tr is None:
            return super().submit(
                kind, root, timeout_s=timeout_s, read_retry=read_retry
            )
        tr.annotate(fleet="process")
        _stitch.trace = tr
        try:
            return super().submit(
                kind, root, timeout_s=timeout_s, read_retry=read_retry
            )
        except Exception:
            if getattr(_stitch, "trace", None) is not None:
                # every replica refused: the request never left the
                # router — the trace is pure routing time
                tr.finish(status="rejected", stage="route")
            raise
        finally:
            _stitch.trace = None

    def _apply_fault(self, signame: str, rep) -> None:
        i = self.home if rep == "home" else int(rep)
        if not (0 <= i < len(self.replicas)):
            return
        sig = {
            "SIGKILL": signal.SIGKILL,
            "SIGSTOP": signal.SIGSTOP,
            "SIGCONT": signal.SIGCONT,
        }[signame]
        try:
            self.replicas[i].signal(sig)
        except OSError:
            return
        if sig == signal.SIGKILL:
            self.sigkills += 1
            obs.count("serve.procfleet.sigkills", replica=i)
            self._fleet_event("sigkill", replica=i)
        elif sig == signal.SIGSTOP:
            self.sigstops += 1
            obs.count("serve.procfleet.sigstops", replica=i)
            self._fleet_event("sigstop", replica=i)

    # -- write path --------------------------------------------------------

    def submit_update(self, ops, fan_out: bool = True):
        """Route a mutation batch to the HOME child (WAL-before-ack
        unchanged — the child's ``submit_update`` appends before the
        reply exists); once its merge lands, fan the new version out
        as a spooled checkpoint.  The future resolves with the merge
        payload plus ``fanned_out``/``lagging``, exactly the thread
        fleet's contract."""
        home = self.replicas[self.home]
        inner = home.rpc(
            "submit_update", {"ops": [list(o) for o in ops]},
            timeout_s=self.ipc_timeout_s,
        )
        if not fan_out:
            return inner
        outer: Future = Future()

        def _after_merge(f):
            exc = f.exception()
            if exc is not None:
                settle(outer, exc=exc)
                return
            payload = dict(f.result())

            def _settle_unfanned():
                # a close-drain write: the merge is durable and
                # applied on the home, and the fleet is coming down —
                # settle honestly with no fan-out rather than strand
                # the future against a shut-down executor
                payload["fanned_out"] = 0
                payload["lagging"] = self.lagging()
                settle(outer, result=payload)

            def _fan():
                try:
                    payload["fanned_out"] = self.fan_out()
                    payload["lagging"] = self.lagging()
                except Exception as e:
                    settle(outer, exc=e)
                    return
                settle(outer, result=payload)

            if self._closing:
                _settle_unfanned()
                return
            try:
                # off the reader thread: fan-out blocks on further RPCs
                self._fan_pool.submit(_fan)
            except RuntimeError:
                # close() shut the pool between the check above and
                # here: same drain race, same honest settle
                _settle_unfanned()

        inner.add_done_callback(_after_merge)
        return outer

    def fan_out(self) -> int:
        """Propagate the home's CURRENT version: the home spools one
        ``save_version`` checkpoint and every other serving replica
        swaps from the FILE — version payloads never ride the socket.
        Per-replica failures lag visibly (``versions_behind``,
        degraded health) and are retried next fan-out."""
        with self._fan_lock:
            self._fan_gen += 1
            gen = self._fan_gen
            t0 = time.perf_counter()
            path = os.path.join(self.spool_dir, f"fan-{gen:08d}.npz")
            self.replicas[self.home].call(
                "spool_version", {"path": path},
                timeout_s=self.ipc_timeout_s,
            )
            n = 0
            for i, rp in enumerate(self.replicas):
                if i == self.home:
                    self._replica_gen[i] = gen
                    continue
                if i in self._draining or not rp.is_serving():
                    continue
                prev = self._replica_gen[i]
                try:
                    rp.call("swap_from_checkpoint", {"path": path},
                            timeout_s=self.ipc_timeout_s)
                    self._replica_gen[i] = gen
                    n += 1
                    if prev < gen - 1:
                        # a replica that had fallen MORE than one
                        # generation behind just caught up
                        self._fleet_event(
                            "fanout_heal", replica=i, gen=gen, was=prev
                        )
                except Exception:
                    obs.count("serve.procfleet.fanout_failed",
                              replica=i)
                    self._fleet_event("fanout_lag", replica=i, gen=gen)
            self.fanouts += 1
            obs.count("serve.procfleet.fanout")
            obs.observe("serve.procfleet.fanout_s",
                        time.perf_counter() - t0)
            for i in range(len(self.replicas)):
                obs.gauge(
                    "serve.procfleet.versions_behind",
                    gen - self._replica_gen[i], replica=i,
                )
            # spool retention: the current fan file plus its
            # predecessor (a replica mid-swap may still be reading it)
            keep = {f"fan-{g:08d}.npz" for g in (gen, gen - 1)}
            for nm in os.listdir(self.spool_dir):
                if nm.startswith("fan-") and nm not in keep:
                    try:
                        os.unlink(os.path.join(self.spool_dir, nm))
                    except OSError:
                        pass
            return n

    # -- supervision hooks (policy.py drives these) ------------------------

    def _depth(self, i: int) -> int:
        return self.replicas[i].depth()

    def _dead(self, i: int) -> bool:
        """Process-level death: exited (``poll()``), broken pipe, or —
        the hang case a thread fleet cannot have — a live process
        whose heartbeats stopped (``SIGSTOP``, wedged runtime) past
        ``hb_timeout_s``."""
        rp = self.replicas[i]
        if rp.quarantined:
            return False  # already out; _needs_rebuild drives the heal
        if rp.proc is not None and rp.proc.poll() is not None:
            return True
        if rp.broken:
            return True
        return rp.heartbeat_age() > self.hb_timeout_s

    def _replace_allowed(self, i: int) -> bool:
        return time.monotonic() >= self._respawn_next.get(i, 0.0)

    def _replace_failed(self, i: int) -> None:
        """Capped-backoff respawn retry: the fleet keeps serving
        degraded on the survivors; the slot is re-attempted at the
        backed-off deadline, never in a hot loop, and the router
        never crashes."""
        self.respawn_failures += 1
        b = self._respawn_backoff.get(i, self._respawn_base_s)
        self._respawn_next[i] = time.monotonic() + b
        self._respawn_backoff[i] = min(2 * b, self._respawn_cap_s)
        obs.count("serve.procfleet.respawn_failed", replica=i)

    def _replace_ok(self, i: int) -> None:
        self._respawn_backoff.pop(i, None)
        self._respawn_next.pop(i, None)

    # -- the fleet observability plane (round 18) --------------------------

    def _observe_fleet(self) -> None:
        """Supervisor-tick gauges: heartbeat age per replica is the
        hang detector's number, and a scrape must see it WITHOUT
        anyone calling ``health()`` (the autoscaler's sensors read
        /metrics, not the stats RPC)."""
        if not obs.ENABLED:
            return
        obs.gauge("serve.procfleet.replicas", len(self.replicas))
        for i, rp in enumerate(self.replicas):
            obs.gauge("serve.procfleet.heartbeat_age_s",
                      rp.heartbeat_age(), replica=i)

    def _fleet_event(self, name: str, **fields) -> None:
        """Append one supervision event to the fleetlog + the flight
        recorder ring; quarantine/promotion additionally dump the ring
        so the post-mortem snapshot sits next to the timeline entry.
        Gated on obs.ENABLED (the zero-cost contract: disabled obs
        leaves no fleetlog file and no recorder traffic)."""
        if not obs.ENABLED:
            return
        if name == "replica_dead":
            i = fields.get("replica")
            rp = self.replicas[i] if i is not None else None
            if rp is not None:
                # enrich with the CAUSE the supervisor saw, so the
                # timeline distinguishes a SIGKILL'd corpse from a
                # SIGSTOP'd zombie post-mortem
                code = rp.proc.poll() if rp.proc is not None else None
                if code is not None:
                    fields["cause"] = "exited"
                    fields["exit_code"] = code
                elif rp.broken:
                    fields["cause"] = "channel_broken"
                elif rp.quarantined:
                    fields["cause"] = "rebuild_pending"
                else:
                    fields["cause"] = "heartbeat_miss"
                    fields["heartbeat_age_s"] = round(
                        rp.heartbeat_age(), 4
                    )
        self.fleetlog.event(name, **fields)
        self.recorder.record(f"fleet.{name}", **fields)
        if name in ("quarantine", "promotion"):
            self.recorder.dump(reason=name, force=True)

    def metrics_records(self) -> list[dict]:
        """The federated fleet registry view the ``/metrics`` scrape
        renders: the router's own snapshot plus every replica's last
        heartbeat-piggybacked child snapshot, relabeled ``replica=i``
        — one scrape sees the whole fleet."""
        recs = list(obs.metrics_snapshot())
        for i, rp in enumerate(self.replicas):
            for r in rp.last_metrics or ():
                r2 = dict(r)
                labels = dict(r2.get("labels") or {})
                labels["replica"] = i
                r2["labels"] = labels
                recs.append(r2)
        return recs

    def serve_metrics(self, port: int = 0,
                      host: str = "127.0.0.1") -> int:
        """Start the fleet-wide Prometheus scrape surface
        (``/metrics`` + ``/healthz`` + ``/statz``) — the one scrape
        covering router AND child-process series (via
        ``metrics_records``).  ``port=0`` binds an ephemeral port; the
        bound port is returned.  Stopped by ``close()``."""
        from ..obs import export

        return export.attach_scrape(self, port=port, host=host)

    def promote(self, new_home: int | None = None) -> int:
        """Dead-home failover over IPC: quarantine the dead home
        (in-flight futures fail honestly; acknowledged writes are in
        the WAL), then one ``promote`` RPC brings a survivor to the
        WAL frontier (recover + swap + ``attach_durability`` +
        re-warm, all inside ITS process) — same single-lineage
        guarantee as the thread fleet, held by the same files."""
        with self._sup_lock:
            old = self.home
            self.replicas[old].quarantine(ReplicaDeadError(
                f"home replica {old} died; promoting at the WAL "
                "frontier (acknowledged writes are durable and "
                "replayed there)"
            ))
            self._fleet_event(
                "quarantine", replica=old, reason="dead_home"
            )
            if new_home is None:
                cands = [
                    i for i in self._route_order()
                    if i != old and self.replicas[i].is_serving()
                ]
                if not cands:
                    raise RuntimeError(
                        "no serving replica available to promote"
                    )
                new_home = cands[0]
            try:
                self.replicas[new_home].call(
                    "promote", {"wal_dir": self.wal_dir},
                    timeout_s=self.boot_timeout_s,
                )
            except Exception as e:
                # the survivor's state is UNKNOWN — a lost/late reply
                # may mean it ALREADY attached the WAL.  Two processes
                # must never own one log (their checkpoint truncations
                # would orphan each other's fds and lose acknowledged
                # writes), so collapse the candidate too: quarantine's
                # SIGKILL releases any attach, and the replace loop
                # rebuilds both slots from the durable files.
                self.replicas[new_home].quarantine(ReplicaDeadError(
                    f"replica {new_home} promotion state unknown "
                    f"({type(e).__name__}); collapsed to preserve "
                    "single WAL ownership"
                ))
                self._needs_rebuild.add(new_home)
                self._fleet_event(
                    "quarantine", replica=new_home,
                    reason="promote_unknown",
                )
                raise RuntimeError(
                    f"promotion of replica {new_home} failed: {e}"
                ) from e
            self.home = new_home
            self._replica_gen[new_home] = self._fan_gen
            self.promotions += 1
            obs.count("serve.procfleet.promotions")
            self._fleet_event(
                "promotion", old_home=old, new_home=new_home
            )
            # surviving replicas may be missing acknowledged writes
            # the dead home never fanned out: propagate the recovered
            # frontier now (best-effort; failures lag visibly)
            try:
                self.fan_out()
            except Exception:
                obs.count(self._OBS + ".supervisor",
                          action="fanout_error")
            return new_home

    def _replace_replica(self, i: int) -> None:
        """Respawn a dead slot warm from checkpoint+WAL: quarantine
        (SIGKILL — also the answer to a SIGSTOPped zombie), then a
        fresh subprocess boots via recovery and warms from the shared
        plan store before re-admission."""
        old = self.replicas[i]
        if not old.quarantined:
            old.quarantine(ReplicaDeadError(
                f"replica {i} process died; the fleet supervisor is "
                "respawning a replacement"
            ))
            self._fleet_event("quarantine", replica=i, reason="respawn")
        rp = self._spawn(i, recover=True, home=(i == self.home))
        self.replicas[i] = rp
        self._replica_gen[i] = self._fan_gen
        self._needs_rebuild.discard(i)
        self.replacements += 1
        obs.count("serve.procfleet.respawns", replica=i)
        self._fleet_event(
            "respawn", replica=i,
            pid=(rp.proc.pid if rp.proc is not None else None),
            home=(i == self.home),
        )

    # -- lifecycle / introspection -----------------------------------------

    def warmup(self, **kw) -> dict:
        payload = {}
        if kw.get("widths") is not None:
            payload["widths"] = list(kw["widths"])
        return {
            i: rp.call("warmup", payload,
                       timeout_s=self.boot_timeout_s)
            for i, rp in enumerate(self.replicas)
            if rp.is_serving()
        }

    def trace_marks(self) -> dict:
        """Per-replica engine trace marks over IPC — the zero-retrace
        assertion's first half (``retraces_since`` is the second)."""
        return {
            i: rp.call("trace_mark")["mark"]
            for i, rp in enumerate(self.replicas) if rp.is_serving()
        }

    def retraces_since(self, marks: dict) -> int:
        return sum(
            self.replicas[i].call(
                "retraces_since", {"mark": m}
            )["retraces"]
            for i, m in marks.items()
            if self.replicas[i].is_serving()
        )

    def close(self, drain: bool = True, timeout: float = 30.0) -> None:
        # flag BEFORE the pool shutdown: a write merging during the
        # home's close-drain settles un-fanned instead of racing a
        # shut-down executor (its future must never strand)
        self._closing = True
        if self._scrape is not None:
            from ..obs import export

            export.detach_scrape(self)
        self.stop_supervisor(timeout)
        self._fan_pool.shutdown(wait=True)
        order = [
            i for i in range(len(self.replicas)) if i != self.home
        ] + [self.home]
        for i in order:
            self.replicas[i].close(drain=drain, timeout=timeout)

    def __enter__(self) -> "ProcessFleet":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def stats(self) -> dict:
        return {
            "replicas": len(self.replicas),
            "home": self.home,
            "routed": list(self.submitted),
            "spillovers": self.spillovers,
            "fanouts": self.fanouts,
            "lagging": self.lagging(),
            "promotions": self.promotions,
            "replacements": self.replacements,
            "respawn_failures": self.respawn_failures,
            "read_retries": self.read_retries,
            "sigkills": self.sigkills,
            "sigstops": self.sigstops,
            "draining": sorted(self._draining),
            "supervisor_alive": self._supervisor_alive(),
            "wal_dir": self.wal_dir,
            "fleetlog": self.fleetlog.describe(),
            "flightrec": self.recorder.describe(),
            "per_replica": {
                i: {
                    "pid": (rp.proc.pid if rp.proc is not None
                            else None),
                    "alive": rp.is_serving(),
                    "quarantined": rp.quarantined,
                    "rpcs": rp.rpcs,
                    "ipc_timeouts": rp.ipc_timeouts,
                    "heartbeat_age_s": round(rp.heartbeat_age(), 4),
                    "last_hb": dict(rp.last_hb),
                }
                for i, rp in enumerate(self.replicas)
            },
        }

    def health(self) -> dict:
        """Pollable fleet health: per-replica status derived from
        process liveness + heartbeat freshness (``heartbeat_age_s``
        is the hang detector's number, gauged per replica), folded
        with the shared policy's ok/degraded/down rule."""
        per = {}
        for i, rp in enumerate(self.replicas):
            age = rp.heartbeat_age()
            obs.gauge("serve.procfleet.heartbeat_age_s", age,
                      replica=i)
            if not rp.is_serving():
                status = "down"
            elif age > self.hb_timeout_s:
                status = "down"  # alive but silent: wedged
            elif not rp.last_hb.get("serving", True):
                status = "down"
            elif rp.last_hb.get("worker_errors", 0) > 0:
                status = "degraded"
            else:
                status = "ok"
            per[i] = {
                "status": status,
                "heartbeat_age_s": round(age, 4),
                "pid": rp.proc.pid if rp.proc is not None else None,
                "depth": rp.depth(),
                "graph_version": rp.last_hb.get("graph_version"),
                "wal_frontier": rp.last_hb.get("wal_frontier"),
                "ipc_timeouts": rp.ipc_timeouts,
            }
        statuses = {h["status"] for h in per.values()}
        lagging = self.lagging()
        return {
            "status": self._fold_status(statuses, lagging),
            "replicas": per,
            "home": self.home,
            "lagging": lagging,
            "draining": sorted(self._draining),
            "supervisor_alive": self._supervisor_alive(),
            "durable": True,
        }
