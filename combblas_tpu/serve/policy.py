"""Shared fleet policy: routing, read retry, supervision (round 17).

``FleetRouter`` (thread-hosted replicas, ``fleet.py``) and
``ProcessFleet`` (subprocess replicas, ``procfleet.py``) are the same
SERVICE with different crash domains: least-loaded read routing with
spillover, bounded read retry on the next-best replica, one HOME
write lane, supervision that detects dead replicas / promotes a dead
home / rebuilds replacements.  Before this module each of those
behaviors lived inline in ``fleet.py`` and a process fleet would have
forked them; now both front ends subclass :class:`ReplicaFleetBase`
and differ only in the LIVENESS and HEAL verbs:

* ``_depth(i)`` / ``_serving(i)`` — routing-time load and liveness
  (queue depth vs in-flight RPCs; worker-thread alive vs process
  alive + heartbeat fresh);
* ``_dead(i)`` — supervision-time death (thread died vs process
  exited / pipe broken / heartbeat timed out);
* ``promote()`` / ``_replace_replica(i)`` — the heal actions (in-
  process rebuild vs respawn-from-checkpoint+WAL over IPC).

Obs series are emitted under the subclass's ``_OBS`` prefix
(``serve.fleet`` / ``serve.procfleet``) so the two fleets' routing
and supervision disposition stay separately pageable; the series
shapes are identical (see the obs/metrics.py catalog).
"""

from __future__ import annotations

import itertools
import threading
from concurrent.futures import Future

from .. import obs
from .batcher import settle
from .scheduler import BackpressureError


class ReplicaDeadError(RuntimeError):
    """A replica died (worker thread or OS process) and the supervisor
    took it out of service: its pending futures fail with this.  With
    a WAL attached the ACKNOWLEDGED writes themselves are durable
    (recovery / promotion replays them) — only the futures fail,
    honestly."""


class StaleEpochError(RuntimeError):
    """A slice was asked to advance a batch epoch it holds no resident
    loop state for (it respawned or restarted mid-batch).  This is a
    healthy slice reporting a protocol fact, NOT a death: the router
    replays the whole batch under a fresh epoch (re-seeding every
    slice) without quarantining anyone — the round-21 slice-resident
    hop-state contract."""


class ReplicaFleetBase:
    """Routing + supervision policy over ``self.replicas`` (anything
    with ``submit(kind, root, timeout_s=)`` returning a Future).

    Subclasses call :meth:`_init_policy` after populating
    ``self.replicas`` and ``self.home``, and implement the liveness /
    heal hooks (module docstring).  Everything here is crash-domain
    agnostic by construction — it only ever calls the hooks and
    ``replicas[i].submit``.
    """

    #: Obs series prefix — ``serve.fleet`` (threads) or
    #: ``serve.procfleet`` (processes); the series shapes match.
    _OBS = "serve.fleet"

    def _init_policy(self) -> None:
        self._rr = itertools.count()
        self.submitted: list[int] = [0] * len(self.replicas)
        self.spillovers = 0
        self.fanouts = 0
        self.promotions = 0
        self.replacements = 0
        self.read_retries = 0
        # fan-out generation accounting: versions_behind[i] =
        # _fan_gen - _replica_gen[i] (0 = replica serves the home's
        # latest fanned-out version)
        self._fan_gen = 0
        self._replica_gen = [0] * len(self.replicas)
        self._draining: set[int] = set()
        self._drain_gen: dict[int, int] = {}  # fan gen at drain time
        # slots whose quarantined replica still awaits a replacement:
        # STICKY until the heal succeeds — _dead() can go False the
        # moment quarantine closes admissions, so without this a
        # transient rebuild failure would be forgotten forever
        self._needs_rebuild: set[int] = set()
        self._sup_lock = threading.RLock()  # serializes heal actions
        self._sup_thread: threading.Thread | None = None
        self._sup_stop = threading.Event()
        self._sup_interval = 0.05

    # -- liveness / heal hooks (subclass responsibility) -------------------

    def _depth(self, i: int) -> int:
        """Routing-time load of replica ``i``."""
        return self.replicas[i].scheduler.depth()

    def _serving(self, i: int) -> bool:
        """Routing-time liveness of replica ``i`` (cheap; called per
        submit)."""
        return self.replicas[i].is_serving()

    def _dead(self, i: int) -> bool:  # pragma: no cover - abstract
        raise NotImplementedError

    def promote(self, new_home: int | None = None) -> int:
        raise NotImplementedError  # pragma: no cover - abstract

    def _replace_replica(self, i: int) -> None:
        raise NotImplementedError  # pragma: no cover - abstract

    def _replace_allowed(self, i: int) -> bool:
        """Gate one heal attempt (ProcessFleet: capped-backoff retry
        after repeated respawn failures — the fleet keeps serving
        degraded on survivors instead of respawn-storming)."""
        return True

    def _replace_failed(self, i: int) -> None:
        """Heal-attempt failure hook (backoff bookkeeping)."""

    def _replace_ok(self, i: int) -> None:
        """Heal-success hook (backoff reset)."""

    # -- observability hooks (round 18) ------------------------------------

    def _observe_fleet(self) -> None:
        """Per-supervisor-tick telemetry hook: subclasses gauge their
        continuously-scrape-visible liveness series here (ProcessFleet:
        ``serve.procfleet.heartbeat_age_s{replica=}``) so a scrape sees
        freshness without anyone calling ``health()``.  Default: none —
        the base class has no liveness signal of its own."""

    def _fleet_event(self, name: str, **fields) -> None:
        """Supervision-timeline hook: subclasses with an event log
        (ProcessFleet's fleetlog) append ``name`` + fields; the base
        class drops events — the policy layer narrates, the front end
        decides whether anyone is listening."""

    # -- read path ---------------------------------------------------------

    def _route_order(self) -> list[int]:
        """SERVING replica indices, least queue depth first; ties
        broken by a rotating offset so equal-depth replicas share
        evenly.  Dead, closed, and draining replicas are SKIPPED —
        a dead replica must not attract traffic purely by its empty
        queue depth."""
        alive = [
            i for i in range(len(self.replicas))
            if i not in self._draining and self._serving(i)
        ]
        if not alive:
            # nothing serves: route everywhere so the caller sees the
            # real rejection instead of an empty-fleet IndexError
            alive = list(range(len(self.replicas)))
        depths = {i: self._depth(i) for i in alive}
        off = next(self._rr) % len(self.replicas)
        return sorted(
            alive,
            key=lambda i: (depths[i], (i - off) % len(self.replicas)),
        )

    def submit(self, kind: str, root, timeout_s: float | None = None,
               read_retry: int = 1, trace=None):
        """Route one query to the least-loaded serving replica,
        spilling to the next on backpressure/breaker rejection; raises
        the LAST rejection only when every replica refused.

        ``read_retry`` bounds execution-side retries: a future that
        fails with a replica-level error (worker/process death,
        injected fault, poison-exhausted batch, IPC timeout — NOT
        backpressure, malformed-root, or deadline errors) is
        re-submitted once per budget unit to the next-best OTHER
        replica before the caller sees the failure.  Reads only —
        writes have exactly one home lineage and never retry
        implicitly.

        ``trace`` (round 19) forwards the net frontend's live trace
        object to the replica that ADMITS the request (spillover
        attempts carry it along; read-retries do not — the trace
        narrates the original execution).  Passed as a conditional
        keyword so replica classes with the narrower signature
        (ReplicaProc, which stitches by rid instead) stay untouched
        when no trace rides."""
        tr_kw = {} if trace is None else {"trace": trace}
        last_exc: Exception | None = None
        for i in self._route_order():
            try:
                fut = self.replicas[i].submit(
                    kind, root, timeout_s=timeout_s, **tr_kw
                )
            except (BackpressureError, RuntimeError) as e:
                # backpressure/breaker — or a replica quarantined/
                # closed between _route_order's liveness check and
                # this submit: spill to the next replica either way,
                # matching the retry path's exception taxonomy
                self.spillovers += 1
                obs.count(self._OBS + ".spillover", replica=i)
                last_exc = e
                continue
            self.submitted[i] += 1
            obs.count(self._OBS + ".submitted", replica=i)
            if read_retry > 0:
                return self._with_read_retry(
                    fut, kind, root, timeout_s, i, read_retry
                )
            return fut
        raise last_exc  # every replica rejected

    def _with_read_retry(self, fut, kind, root, timeout_s,
                         replica: int, budget: int) -> Future:
        """Wrap a submitted read's future: on an execution-side
        failure, re-submit to the next-best OTHER serving replica
        (bounded by ``budget``); the outer future sees the retried
        outcome.  Admission-level rejections (backpressure/breaker),
        malformed roots (ValueError) and expired deadlines
        (TimeoutError) are NOT retried — they would fail identically
        or lie about the deadline."""
        outer: Future = Future()

        def _done(f):
            exc = f.exception()
            if exc is None:
                settle(outer, result=f.result())
                return
            if budget <= 0 or isinstance(
                exc, (BackpressureError, ValueError, TimeoutError)
            ):
                settle(outer, exc=exc)
                return
            for j in self._route_order():
                if j == replica:
                    continue
                try:
                    f2 = self.replicas[j].submit(
                        kind, root, timeout_s=timeout_s
                    )
                except (BackpressureError, RuntimeError):
                    continue
                self.read_retries += 1
                self.submitted[j] += 1
                obs.count(self._OBS + ".read_retry", replica=j)
                inner = self._with_read_retry(
                    f2, kind, root, timeout_s, j, budget - 1
                )
                inner.add_done_callback(
                    lambda g: settle(
                        outer,
                        result=(
                            g.result() if g.exception() is None
                            else None
                        ),
                        exc=g.exception(),
                    )
                )
                return
            settle(outer, exc=exc)  # nowhere to retry

        fut.add_done_callback(_done)
        return outer

    def submit_many(self, kind: str, roots,
                    timeout_s: float | None = None) -> list:
        """Bulk submit through the router. Unlike a single server's
        prefix semantics, spillover means a LATER root can still land
        after one was rejected fleet-wide — so each rejected root fails
        its OWN future and admission continues."""
        out = []
        for r in roots:
            try:
                out.append(self.submit(kind, r, timeout_s=timeout_s))
            except BackpressureError as e:
                f: Future = Future()
                f.set_exception(e)
                out.append(f)
        return out

    def lagging(self) -> list[int]:
        """Replica indices serving an older version than the home's
        latest fan-out (failed/skipped rebuilds — retried next
        fan-out; degraded ``health()`` while non-empty)."""
        return [
            i for i in range(len(self.replicas))
            if i != self.home
            and self._replica_gen[i] < self._fan_gen
        ]

    # -- supervision -------------------------------------------------------

    def start_supervisor(self, interval_s: float = 0.05):
        """Start the liveness supervisor thread: every ``interval_s``
        it runs ``supervise_once()`` — dead-replica detection,
        replacement rebuilds, home promotion.  Idempotent; stopped by
        ``close()`` / ``stop_supervisor()``."""
        with self._sup_lock:
            if self._sup_thread is None or not self._sup_thread.is_alive():
                self._sup_stop.clear()
                self._sup_interval = float(interval_s)
                self._sup_thread = threading.Thread(
                    target=self._sup_loop, name="combblas-fleet-sup",
                    daemon=True,
                )
                self._sup_thread.start()
        return self

    def stop_supervisor(self, timeout: float = 10.0) -> None:
        t = self._sup_thread
        if t is None:
            return
        self._sup_stop.set()
        t.join(timeout)
        if t.is_alive():
            raise TimeoutError(
                f"fleet supervisor did not stop within {timeout}s"
            )
        self._sup_thread = None

    def _sup_loop(self) -> None:
        while not self._sup_stop.is_set():
            try:
                self.supervise_once()
            except Exception as e:  # the supervisor must outlive any
                # one heal attempt: a failed rebuild is retried on the
                # next tick, visible in the counter — a dead
                # supervisor would silently stop all self-healing
                obs.count(
                    self._OBS + ".supervisor",
                    action="error", exc_type=type(e).__name__,
                )
            self._sup_stop.wait(self._sup_interval)

    def supervise_once(self) -> dict:
        """One supervision pass (the supervisor thread's body, callable
        directly for deterministic tests): detect dead replicas,
        promote a new home first if the HOME died, then rebuild every
        dead replica and re-admit it.  Returns ``{"detected": [...],
        "promoted": new_home | None, "replaced": [...]}``."""
        with self._sup_lock:
            self._observe_fleet()
            dead = [
                i for i in range(len(self.replicas))
                if i not in self._draining
                and (self._dead(i) or i in self._needs_rebuild)
            ]
            out = {"detected": dead, "promoted": None, "replaced": []}
            if not dead:
                return out
            for i in dead:
                if i not in self._needs_rebuild:
                    obs.count(
                        self._OBS + ".supervisor", action="detected"
                    )
                    self._fleet_event(
                        "replica_dead", replica=i, home=(i == self.home)
                    )
                # sticky until the heal succeeds: a transient rebuild
                # failure below must be RETRIED on the next tick, not
                # forgotten (quarantine flips _dead() false)
                self._needs_rebuild.add(i)
            if self.home in dead:
                try:
                    out["promoted"] = self.promote()
                except RuntimeError:
                    # no WAL to promote from (or no surviving
                    # replica, or a transient recovery failure):
                    # promote() already quarantined the home — its
                    # buffered futures failed honestly — and the
                    # replace loop below still rebuilds the slot,
                    # so the write lane comes back instead of
                    # staying down
                    obs.count(
                        self._OBS + ".supervisor",
                        action="promotion_failed",
                    )
                    self._fleet_event(
                        "promotion_failed", replica=self.home
                    )
            for i in dead:
                if not self._replace_allowed(i):
                    continue  # backing off: retried on a later tick
                try:
                    self._replace_replica(i)
                except Exception:
                    # stays in _needs_rebuild: retried next tick
                    self._replace_failed(i)
                    obs.count(
                        self._OBS + ".supervisor",
                        action="replace_error",
                    )
                    self._fleet_event("respawn_failed", replica=i)
                    continue
                self._replace_ok(i)
                out["replaced"].append(i)
                obs.count(self._OBS + ".supervisor", action="replaced")
            return out

    # -- health folding ----------------------------------------------------

    def _fold_status(self, statuses: set, lagging: list) -> str:
        """Fleet status from per-replica statuses: everything ok and
        nothing lagging = ok; anything still serving = degraded; else
        down."""
        if statuses <= {"ok"} and not lagging:
            return "ok"
        if "ok" in statuses or "degraded" in statuses:
            return "degraded"  # something still serves
        return "down"

    def _supervisor_alive(self) -> bool:
        return (
            self._sup_thread is not None
            and self._sup_thread.is_alive()
        )
