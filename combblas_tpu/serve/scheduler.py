"""Admission control + flush policy: the backpressured front door.

A bounded pending queue with reject-with-retry-after admission (a full
queue REFUSES work instead of buffering unboundedly — the load-shedding
half of a serving stack), per-kind deadline-driven flushing (a batch
goes out when it fills its widest lane bucket OR its oldest request has
waited ``max_wait_s``), per-request timeouts, and error isolation: a
malformed root fails ITS future at admission and never contaminates a
batch.

Thread-safe; the api-layer worker loop drives ``pop_ready`` /
``next_deadline``. Everything here is host-side bookkeeping — no JAX in
this module.
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
import time
from collections import deque
from concurrent.futures import Future

from .. import obs
from .batcher import Request, expire, settle


def _bump(d: dict, kind: str, n: int = 1) -> None:
    """Per-kind counter bump (shared by Scheduler and Server)."""
    d[kind] = d.get(kind, 0) + n


class BackpressureError(RuntimeError):
    """Queue full: the caller should back off and retry.

    ``retry_after_s`` is the server's hint — one flush deadline, i.e.
    when capacity is next expected to free up.
    """

    def __init__(self, depth: int, retry_after_s: float):
        super().__init__(
            f"serve queue full ({depth} pending); retry after "
            f"{retry_after_s:.3f}s"
        )
        self.retry_after_s = retry_after_s


class CircuitBreakerOpen(BackpressureError):
    """This kind's breaker is open: recent executions failed
    consecutively, so submits fast-fail instead of queueing work the
    engine will predictably burn a device lane on. A subclass of
    ``BackpressureError`` — retry-after semantics are identical, so
    callers with a backoff loop need no new handling."""

    def __init__(self, kind: str, retry_after_s: float):
        RuntimeError.__init__(
            self,
            f"circuit breaker open for kind {kind!r}; retry after "
            f"{retry_after_s:.3f}s",
        )
        self.kind = kind
        self.retry_after_s = retry_after_s


#: Circuit-breaker states (also the ``serve.breaker.state`` gauge
#: values: closed=0, half_open=1, open=2).
BREAKER_CLOSED = "closed"
BREAKER_HALF_OPEN = "half_open"
BREAKER_OPEN = "open"
_BREAKER_GAUGE = {BREAKER_CLOSED: 0, BREAKER_HALF_OPEN: 1, BREAKER_OPEN: 2}


class CircuitBreaker:
    """Consecutive-failure breaker for one query kind.

    CLOSED counts consecutive top-level batch failures; at
    ``threshold`` it OPENs: admissions fast-fail with
    ``CircuitBreakerOpen`` until ``cooldown_s`` elapses, then the next
    admission flips it HALF_OPEN (a probe is let through). The probe
    batch's outcome decides: success re-CLOSEs (cooldown resets),
    failure re-OPENs with the cooldown doubled (capped at
    ``cooldown_max_s``) — a persistently broken kind backs off
    exponentially instead of retrying at a fixed cadence.

    Failures are recorded at TOP-LEVEL batch granularity by the api
    worker (bisection-recovery sub-batches are not counted), so one
    poisoned request in an otherwise healthy engine cannot open the
    breaker. All methods take an explicit ``now`` for deterministic
    tests; thread-safe.
    """

    def __init__(self, threshold: int = 5, cooldown_s: float = 1.0,
                 cooldown_max_s: float = 30.0):
        if threshold < 1:
            raise ValueError("breaker threshold must be >= 1")
        self.threshold = int(threshold)
        self.cooldown_s = float(cooldown_s)
        self.cooldown_max_s = float(cooldown_max_s)
        self._lock = threading.Lock()
        self.state = BREAKER_CLOSED
        self.failures = 0  # consecutive, while CLOSED
        self.opened_at: float | None = None
        self._cooldown = self.cooldown_s
        self._probe_at: float | None = None  # half-open probe admitted
        self.opened_total = 0
        self.fast_fails = 0

    def admit(self, now: float, kind: str = "") -> bool:
        """May a submit of this kind be admitted right now? An OPEN
        breaker whose cooldown has elapsed flips HALF_OPEN here — the
        admitted request IS the probe, and it is the ONLY one: further
        submits fast-fail until the probe's batch outcome decides (or
        a full cooldown passes without an outcome — a probe that
        expired in queue must not wedge the breaker half-open
        forever)."""
        with self._lock:
            if self.state == BREAKER_OPEN:
                if now - self.opened_at >= self._cooldown:
                    self.state = BREAKER_HALF_OPEN
                    self._probe_at = now
                    obs.gauge("serve.breaker.state",
                              _BREAKER_GAUGE[self.state], kind=kind)
                    return True
                self.fast_fails += 1
                return False
            if self.state == BREAKER_HALF_OPEN:
                if (
                    self._probe_at is None
                    or now - self._probe_at >= self._cooldown
                ):
                    self._probe_at = now  # stale probe: re-probe
                    return True
                self.fast_fails += 1
                return False
            return True  # CLOSED

    def release_probe(self) -> None:
        """Give back a half-open probe slot whose request never made
        it into the queue (queue-full or close() raced the admit) —
        otherwise the kind stays fast-failing for a full cooldown with
        no probe actually in flight."""
        with self._lock:
            if self.state == BREAKER_HALF_OPEN:
                self._probe_at = None

    def retry_after(self, now: float) -> float:
        with self._lock:
            if self.state == BREAKER_OPEN and self.opened_at is not None:
                return max(0.0, self.opened_at + self._cooldown - now)
            if (
                self.state == BREAKER_HALF_OPEN
                and self._probe_at is not None
            ):
                # waiting on the outstanding probe's outcome
                return max(0.0, self._probe_at + self._cooldown - now)
            return 0.0

    def record_success(self, now: float, kind: str = "") -> None:
        closed_now = False
        with self._lock:
            self.failures = 0
            self._probe_at = None
            if self.state != BREAKER_CLOSED:
                self.state = BREAKER_CLOSED
                self._cooldown = self.cooldown_s
                closed_now = True
        if closed_now:  # gauge only on TRANSITION: the steady-state
            # healthy path (one record_success per batch) stays free
            obs.gauge("serve.breaker.state", 0, kind=kind)

    def record_failure(self, now: float, kind: str = "") -> None:
        opened = False  # did THIS call transition to OPEN?
        with self._lock:
            if self.state == BREAKER_HALF_OPEN:
                # the probe failed: back off harder
                self.state = BREAKER_OPEN
                self.opened_at = now
                self._probe_at = None
                self._cooldown = min(2 * self._cooldown,
                                     self.cooldown_max_s)
                self.opened_total += 1
                opened = True
            elif self.state == BREAKER_CLOSED:
                self.failures += 1
                if self.failures >= self.threshold:
                    self.state = BREAKER_OPEN
                    self.opened_at = now
                    self._cooldown = self.cooldown_s
                    self.opened_total += 1
                    opened = True
            else:  # OPEN: a straggler batch admitted pre-open failed —
                # refresh the clock, but it is NOT a new open transition
                self.opened_at = now
            state = self.state
        obs.gauge("serve.breaker.state", _BREAKER_GAUGE[state], kind=kind)
        if opened:
            obs.count("serve.breaker.opened", kind=kind)

    def describe(self, now: float) -> dict:
        with self._lock:
            return {
                "state": self.state,
                "consecutive_failures": self.failures,
                "opened_total": self.opened_total,
                "fast_fails": self.fast_fails,
                "cooldown_s": self._cooldown,
                "retry_after_s": (
                    max(0.0, self.opened_at + self._cooldown - now)
                    if self.state == BREAKER_OPEN else 0.0
                ),
            }


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Policy knobs for one server instance.

    ``lane_widths``: ascending shape buckets a flush may compile/execute
    under (every width here should be covered by ``warmup()`` so
    steady-state serving never traces). ``max_wait_s``: flush deadline —
    the latency a lonely request pays waiting for lane-mates;
    ``per_kind_max_wait`` overrides it per query kind. ``max_queue``
    bounds TOTAL pending requests across kinds (admission control).

    Resilience knobs: ``retry_budget`` is the number of FAILING
    executions one request may ride before its future fails. The
    default (``None``) is computed from the widest lane bucket as
    ``1 + ceil(log2(w_max))`` — exactly a full bisection (width 16:
    16→8→4→2→1 = 5), so one poison request always fails ALONE and its
    lane-mates survive regardless of configured widths. An explicit
    smaller value is the operator's bounded-work/fail-fast choice: a
    batch that exhausts it above width 1 fails innocents alongside the
    poison. ``breaker_threshold`` consecutive
    top-level batch failures open a kind's circuit breaker
    (``None``/0 disables breakers); an open breaker fast-fails submits
    for ``breaker_cooldown_s``, then a half-open probe decides —
    failure doubles the cooldown up to ``breaker_cooldown_max_s``.
    ``worker_backoff_s``/``worker_backoff_max_s`` bound the api
    worker's exponential error backoff (reset on success).
    """

    lane_widths: tuple[int, ...] = (1, 2, 4, 8, 16)
    max_queue: int = 1024
    max_wait_s: float = 0.01
    per_kind_max_wait: dict | None = None
    default_timeout_s: float | None = None
    retry_budget: int | None = None  # None -> 1 + ceil(log2(w_max))
    breaker_threshold: int | None = 5
    breaker_cooldown_s: float = 1.0
    breaker_cooldown_max_s: float = 30.0
    worker_backoff_s: float = 0.05
    worker_backoff_max_s: float = 2.0
    # -- write lane (docs/dynamic.md "Serving writes"): submit_update
    # admits edge mutations into a bounded DeltaBuffer (capacity
    # ``update_buffer``; full = reject with BackpressureError) and a
    # dedicated mutation thread merges a batch when ``update_flush``
    # ops have coalesced OR the oldest has waited ``update_max_delay_s``
    # — reads stay hot on the current version during the whole merge,
    # only the atomic swap takes the execution lock.
    # ``update_autostart=False`` disables the thread (deterministic
    # tests drive ``Server.pump_updates()`` instead).
    update_buffer: int = 4096
    update_flush: int = 64
    update_max_delay_s: float = 0.05
    update_autostart: bool = True

    def __post_init__(self):
        if (
            not self.lane_widths
            or tuple(sorted(self.lane_widths)) != tuple(self.lane_widths)
            or self.lane_widths[0] < 1
        ):
            raise ValueError(
                "lane_widths must be ascending positive ints"
            )
        if self.retry_budget is None:
            # full-bisection budget for the widest configured bucket
            # (frozen dataclass: assign via object.__setattr__)
            object.__setattr__(
                self, "retry_budget",
                1 + max(0, int(self.lane_widths[-1]) - 1).bit_length(),
            )
        if self.retry_budget < 1:
            raise ValueError("retry_budget must be >= 1")
        if not (0 < self.worker_backoff_s <= self.worker_backoff_max_s):
            raise ValueError(
                "need 0 < worker_backoff_s <= worker_backoff_max_s"
            )
        if self.update_buffer < 1 or self.update_flush < 1:
            raise ValueError(
                "update_buffer and update_flush must be >= 1"
            )
        if self.update_max_delay_s <= 0:
            raise ValueError("update_max_delay_s must be > 0")

    def wait_for(self, kind: str) -> float:
        if self.per_kind_max_wait and kind in self.per_kind_max_wait:
            return self.per_kind_max_wait[kind]
        return self.max_wait_s


class Scheduler:
    """Pending-request store with admission control and flush policy."""

    def __init__(self, config: ServeConfig, nrows: int,
                 kinds: tuple[str, ...]):
        self.config = config
        self.nrows = nrows
        self.kinds = kinds
        self._pending: dict[str, deque[Request]] = {
            k: deque() for k in kinds
        }
        self._rid = itertools.count()
        self._lock = threading.Lock()
        self._closed = False
        self.rejected = 0  # backpressure only; breakers count separately
        self.submitted = 0
        # per-kind disposition counters (Server.stats()'s per_kind
        # table) — plain dicts bumped under _lock
        self.rejected_kind: dict[str, int] = {}
        self.invalid_kind: dict[str, int] = {}
        self.timeout_kind: dict[str, int] = {}
        self.breaker_rejected_kind: dict[str, int] = {}
        # per-kind circuit breakers (execution health -> admission
        # fast-fail); the api worker records batch outcomes into these
        self.breakers: dict[str, CircuitBreaker] = (
            {
                k: CircuitBreaker(
                    config.breaker_threshold,
                    config.breaker_cooldown_s,
                    config.breaker_cooldown_max_s,
                )
                for k in kinds
            }
            if config.breaker_threshold else {}
        )

    def close(self) -> None:
        """Refuse all further admissions, PERMANENTLY (set under the
        admission lock, so a submit racing ``Server.close`` either
        lands before the drain or raises — it can never be silently
        stranded)."""
        with self._lock:
            self._closed = True

    @property
    def closed(self) -> bool:
        with self._lock:
            return self._closed

    # -- admission ---------------------------------------------------------

    def depth(self) -> int:
        with self._lock:
            return sum(len(q) for q in self._pending.values())

    def submit(self, kind: str, root, timeout_s: float | None = None,
               now: float | None = None) -> Future:
        """Admit one single-root query; returns its Future.

        Raises ``BackpressureError`` when the queue is full and
        ``ValueError`` for an unknown kind (caller bugs, not load). A
        MALFORMED ROOT is isolated instead: its future carries the
        ValueError and the request never enters a batch.
        """
        if kind not in self._pending:
            raise ValueError(
                f"unknown query kind {kind!r}; engine serves {self.kinds}"
            )
        with self._lock:  # closed check FIRST: close semantics must not
            # depend on whether the root happened to be malformed
            if self._closed:
                raise RuntimeError(
                    "serve.Server is closed; no further admissions"
                )
        now = time.monotonic() if now is None else now
        fut: Future = Future()
        timeout_s = (
            timeout_s if timeout_s is not None
            else self.config.default_timeout_s
        )
        deadline = None if timeout_s is None else now + timeout_s
        # error isolation: a bad root fails its OWN request, not a batch
        try:
            root_i = int(root)
            if root_i != root or not (0 <= root_i < self.nrows):
                raise ValueError(
                    f"root {root!r} outside [0, {self.nrows})"
                )
        except (TypeError, ValueError) as e:
            fut.set_exception(
                e if isinstance(e, ValueError) else ValueError(str(e))
            )
            with self._lock:
                _bump(self.invalid_kind, kind)
            obs.count("serve.requests", kind=kind, status="invalid")
            return fut
        breaker = self.breakers.get(kind)
        if breaker is not None and not breaker.admit(now, kind):
            # fast-fail OUTSIDE the queue lock: an open breaker is an
            # execution-health fact, not a queue-depth one
            with self._lock:
                _bump(self.breaker_rejected_kind, kind)
            obs.count("serve.breaker.fast_fail", kind=kind)
            raise CircuitBreakerOpen(kind, breaker.retry_after(now))
        try:
            with self._lock:
                if self._closed:  # re-check: close() may have raced
                    # the host-side validation above
                    raise RuntimeError(
                        "serve.Server is closed; no further admissions"
                    )
                d = sum(len(q) for q in self._pending.values())
                if d >= self.config.max_queue:
                    self.rejected += 1
                    _bump(self.rejected_kind, kind)
                    obs.count("serve.queue.rejected", kind=kind)
                    raise BackpressureError(
                        d, self.config.wait_for(kind)
                    )
                req = Request(
                    rid=next(self._rid), kind=kind, root=root_i,
                    future=fut, submitted_at=now, deadline=deadline,
                )
                self._pending[kind].append(req)
                self.submitted += 1
                obs.gauge("serve.queue.depth", d + 1)
        except (BackpressureError, RuntimeError):
            if breaker is not None:
                # this submit may have claimed the half-open probe
                # slot in admit() above; it never entered the queue,
                # so give the slot back (no-op unless half-open)
                breaker.release_probe()
            raise
        return fut

    # -- flush policy ------------------------------------------------------

    def _dispatch_by(self, kind: str, r: Request) -> float:
        """Latest time ``r`` should enter a batch: its kind's flush
        deadline, tightened for short per-request timeouts — a request
        whose timeout is under 2x the kind's max-wait dispatches at
        HALF its timeout budget (half for queueing, half for
        execution), instead of being slept past and expired in queue."""
        wait = self.config.wait_for(kind)
        if r.deadline is None:
            return r.submitted_at + wait
        budget = (r.deadline - r.submitted_at) / 2
        return r.submitted_at + min(wait, budget)

    def _kind_deadline(self, kind: str, q) -> float:
        """When this kind must flush: the earliest dispatch-by time of
        any queued request. An O(queue-depth) scan, bounded by
        ``max_queue`` (default 1024 — microseconds of host arithmetic
        next to a device batch); track incrementally if max_queue ever
        grows by orders of magnitude."""
        return min(self._dispatch_by(kind, r) for r in q)

    def next_deadline(self) -> float | None:
        """Absolute time of the earliest pending flush, or None when
        idle (see ``_kind_deadline`` for what counts as a deadline)."""
        with self._lock:
            deadlines = [
                self._kind_deadline(k, q)
                for k, q in self._pending.items() if q
            ]
        return min(deadlines) if deadlines else None

    def has_ready(self, now: float | None = None) -> bool:
        """True when some kind is flushable RIGHT NOW (full widest
        bucket or dispatch deadline reached) — the worker checks this
        under its wake lock before sleeping, closing the window where a
        burst's notify lands while no one is waiting."""
        now = time.monotonic() if now is None else now
        wmax = self.config.lane_widths[-1]
        with self._lock:
            return any(
                q and (
                    len(q) >= wmax or now >= self._kind_deadline(k, q)
                )
                for k, q in self._pending.items()
            )

    def pop_ready(self, now: float | None = None,
                  force: bool = False) -> list[list[Request]]:
        """Batches due for execution: a kind flushes when it can fill
        the widest lane bucket, when its oldest request has aged past
        the kind's flush deadline, or unconditionally under ``force``
        (drain/close). Expired requests are timed out here, before
        batching. Returns a list of per-kind request lists (each at most
        the widest bucket — a deep backlog flushes over several calls).
        """
        now = time.monotonic() if now is None else now
        wmax = self.config.lane_widths[-1]
        out: list[list[Request]] = []
        timed_out: list[Request] = []
        with self._lock:
            for kind, q in self._pending.items():
                # full-queue sweep for DEAD requests — expired (even
                # BEHIND a fresh head) or client-cancelled: neither may
                # ride into a batch and waste a device lane or trigger
                # a premature flush; any() guards the rebuild off the
                # common all-live path. Expired requests are only
                # COLLECTED here — settling runs done-callbacks
                # synchronously, and a callback that re-enters submit()
                # would deadlock on this non-reentrant lock
                def dead(r):
                    return r.expired(now) or r.future.done()

                if any(dead(r) for r in q):
                    live = [r for r in q if not dead(r)]
                    for req in q:
                        if req.future.done():  # client cancel/settle
                            obs.count(
                                "serve.requests", kind=kind,
                                status="cancelled",
                            )
                        elif req.expired(now):
                            timed_out.append(req)
                    q.clear()
                    q.extend(live)
                while q and (
                    force
                    or len(q) >= wmax
                    or now >= self._kind_deadline(kind, q)
                ):
                    take = min(len(q), wmax)
                    out.append([q.popleft() for _ in range(take)])
            obs.gauge(
                "serve.queue.depth",
                sum(len(q) for q in self._pending.values()),
            )
        if timed_out:
            with self._lock:
                for req in timed_out:
                    _bump(self.timeout_kind, req.kind)
        for req in timed_out:  # settle OUTSIDE the lock (see above;
            # the per-kind bump already happened under it)
            expire(req, "expired in queue")
        return out

    def drain(self) -> list[list[Request]]:
        """Everything still pending, as batches (close/shutdown path)."""
        return self.pop_ready(force=True)

    def fail_pending(self, exc: Exception) -> None:
        """Fail every queued request (server shutdown without drain).
        Settlement happens after the lock is released — done-callbacks
        run synchronously and may re-enter the scheduler."""
        drained: list[Request] = []
        with self._lock:
            for q in self._pending.values():
                while q:
                    drained.append(q.popleft())
        for req in drained:
            settle(req.future, exc=exc)
