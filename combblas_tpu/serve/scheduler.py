"""Admission control + flush policy: the backpressured front door.

A bounded pending queue with reject-with-retry-after admission (a full
queue REFUSES work instead of buffering unboundedly — the load-shedding
half of a serving stack), per-kind deadline-driven flushing (a batch
goes out when it fills its widest lane bucket OR its oldest request has
waited ``max_wait_s``), per-request timeouts, and error isolation: a
malformed root fails ITS future at admission and never contaminates a
batch.

Thread-safe; the api-layer worker loop drives ``pop_ready`` /
``next_deadline``. Everything here is host-side bookkeeping — no JAX in
this module.
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
import time
from collections import deque
from concurrent.futures import Future

from .. import obs
from .batcher import Request, settle


class BackpressureError(RuntimeError):
    """Queue full: the caller should back off and retry.

    ``retry_after_s`` is the server's hint — one flush deadline, i.e.
    when capacity is next expected to free up.
    """

    def __init__(self, depth: int, retry_after_s: float):
        super().__init__(
            f"serve queue full ({depth} pending); retry after "
            f"{retry_after_s:.3f}s"
        )
        self.retry_after_s = retry_after_s


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Policy knobs for one server instance.

    ``lane_widths``: ascending shape buckets a flush may compile/execute
    under (every width here should be covered by ``warmup()`` so
    steady-state serving never traces). ``max_wait_s``: flush deadline —
    the latency a lonely request pays waiting for lane-mates;
    ``per_kind_max_wait`` overrides it per query kind. ``max_queue``
    bounds TOTAL pending requests across kinds (admission control).
    """

    lane_widths: tuple[int, ...] = (1, 2, 4, 8, 16)
    max_queue: int = 1024
    max_wait_s: float = 0.01
    per_kind_max_wait: dict | None = None
    default_timeout_s: float | None = None

    def __post_init__(self):
        if (
            not self.lane_widths
            or tuple(sorted(self.lane_widths)) != tuple(self.lane_widths)
            or self.lane_widths[0] < 1
        ):
            raise ValueError(
                "lane_widths must be ascending positive ints"
            )

    def wait_for(self, kind: str) -> float:
        if self.per_kind_max_wait and kind in self.per_kind_max_wait:
            return self.per_kind_max_wait[kind]
        return self.max_wait_s


class Scheduler:
    """Pending-request store with admission control and flush policy."""

    def __init__(self, config: ServeConfig, nrows: int,
                 kinds: tuple[str, ...]):
        self.config = config
        self.nrows = nrows
        self.kinds = kinds
        self._pending: dict[str, deque[Request]] = {
            k: deque() for k in kinds
        }
        self._rid = itertools.count()
        self._lock = threading.Lock()
        self._closed = False
        self.rejected = 0
        self.submitted = 0

    def close(self) -> None:
        """Refuse all further admissions, PERMANENTLY (set under the
        admission lock, so a submit racing ``Server.close`` either
        lands before the drain or raises — it can never be silently
        stranded)."""
        with self._lock:
            self._closed = True

    @property
    def closed(self) -> bool:
        with self._lock:
            return self._closed

    # -- admission ---------------------------------------------------------

    def depth(self) -> int:
        with self._lock:
            return sum(len(q) for q in self._pending.values())

    def submit(self, kind: str, root, timeout_s: float | None = None,
               now: float | None = None) -> Future:
        """Admit one single-root query; returns its Future.

        Raises ``BackpressureError`` when the queue is full and
        ``ValueError`` for an unknown kind (caller bugs, not load). A
        MALFORMED ROOT is isolated instead: its future carries the
        ValueError and the request never enters a batch.
        """
        if kind not in self._pending:
            raise ValueError(
                f"unknown query kind {kind!r}; engine serves {self.kinds}"
            )
        with self._lock:  # closed check FIRST: close semantics must not
            # depend on whether the root happened to be malformed
            if self._closed:
                raise RuntimeError(
                    "serve.Server is closed; no further admissions"
                )
        now = time.monotonic() if now is None else now
        fut: Future = Future()
        timeout_s = (
            timeout_s if timeout_s is not None
            else self.config.default_timeout_s
        )
        deadline = None if timeout_s is None else now + timeout_s
        # error isolation: a bad root fails its OWN request, not a batch
        try:
            root_i = int(root)
            if root_i != root or not (0 <= root_i < self.nrows):
                raise ValueError(
                    f"root {root!r} outside [0, {self.nrows})"
                )
        except (TypeError, ValueError) as e:
            fut.set_exception(
                e if isinstance(e, ValueError) else ValueError(str(e))
            )
            obs.count("serve.requests", kind=kind, status="invalid")
            return fut
        with self._lock:
            if self._closed:  # re-check: close() may have raced the
                # host-side validation above
                raise RuntimeError(
                    "serve.Server is closed; no further admissions"
                )
            d = sum(len(q) for q in self._pending.values())
            if d >= self.config.max_queue:
                self.rejected += 1
                obs.count("serve.queue.rejected", kind=kind)
                raise BackpressureError(d, self.config.wait_for(kind))
            req = Request(
                rid=next(self._rid), kind=kind, root=root_i, future=fut,
                submitted_at=now, deadline=deadline,
            )
            self._pending[kind].append(req)
            self.submitted += 1
            obs.gauge("serve.queue.depth", d + 1)
        return fut

    # -- flush policy ------------------------------------------------------

    def _dispatch_by(self, kind: str, r: Request) -> float:
        """Latest time ``r`` should enter a batch: its kind's flush
        deadline, tightened for short per-request timeouts — a request
        whose timeout is under 2x the kind's max-wait dispatches at
        HALF its timeout budget (half for queueing, half for
        execution), instead of being slept past and expired in queue."""
        wait = self.config.wait_for(kind)
        if r.deadline is None:
            return r.submitted_at + wait
        budget = (r.deadline - r.submitted_at) / 2
        return r.submitted_at + min(wait, budget)

    def _kind_deadline(self, kind: str, q) -> float:
        """When this kind must flush: the earliest dispatch-by time of
        any queued request. An O(queue-depth) scan, bounded by
        ``max_queue`` (default 1024 — microseconds of host arithmetic
        next to a device batch); track incrementally if max_queue ever
        grows by orders of magnitude."""
        return min(self._dispatch_by(kind, r) for r in q)

    def next_deadline(self) -> float | None:
        """Absolute time of the earliest pending flush, or None when
        idle (see ``_kind_deadline`` for what counts as a deadline)."""
        with self._lock:
            deadlines = [
                self._kind_deadline(k, q)
                for k, q in self._pending.items() if q
            ]
        return min(deadlines) if deadlines else None

    def has_ready(self, now: float | None = None) -> bool:
        """True when some kind is flushable RIGHT NOW (full widest
        bucket or dispatch deadline reached) — the worker checks this
        under its wake lock before sleeping, closing the window where a
        burst's notify lands while no one is waiting."""
        now = time.monotonic() if now is None else now
        wmax = self.config.lane_widths[-1]
        with self._lock:
            return any(
                q and (
                    len(q) >= wmax or now >= self._kind_deadline(k, q)
                )
                for k, q in self._pending.items()
            )

    def pop_ready(self, now: float | None = None,
                  force: bool = False) -> list[list[Request]]:
        """Batches due for execution: a kind flushes when it can fill
        the widest lane bucket, when its oldest request has aged past
        the kind's flush deadline, or unconditionally under ``force``
        (drain/close). Expired requests are timed out here, before
        batching. Returns a list of per-kind request lists (each at most
        the widest bucket — a deep backlog flushes over several calls).
        """
        now = time.monotonic() if now is None else now
        wmax = self.config.lane_widths[-1]
        out: list[list[Request]] = []
        timed_out: list[Request] = []
        with self._lock:
            for kind, q in self._pending.items():
                # full-queue sweep for DEAD requests — expired (even
                # BEHIND a fresh head) or client-cancelled: neither may
                # ride into a batch and waste a device lane or trigger
                # a premature flush; any() guards the rebuild off the
                # common all-live path. Expired requests are only
                # COLLECTED here — settling runs done-callbacks
                # synchronously, and a callback that re-enters submit()
                # would deadlock on this non-reentrant lock
                def dead(r):
                    return r.expired(now) or r.future.done()

                if any(dead(r) for r in q):
                    live = [r for r in q if not dead(r)]
                    for req in q:
                        if req.future.done():  # client cancel/settle
                            obs.count(
                                "serve.requests", kind=kind,
                                status="cancelled",
                            )
                        elif req.expired(now):
                            timed_out.append(req)
                    q.clear()
                    q.extend(live)
                while q and (
                    force
                    or len(q) >= wmax
                    or now >= self._kind_deadline(kind, q)
                ):
                    take = min(len(q), wmax)
                    out.append([q.popleft() for _ in range(take)])
            obs.gauge(
                "serve.queue.depth",
                sum(len(q) for q in self._pending.values()),
            )
        for req in timed_out:  # settle OUTSIDE the lock (see above)
            settle(req.future, exc=TimeoutError(
                f"request {req.rid} ({req.kind} root={req.root}) "
                "expired in queue"
            ))
            obs.count("serve.requests", kind=req.kind, status="timeout")
        return out

    def drain(self) -> list[list[Request]]:
        """Everything still pending, as batches (close/shutdown path)."""
        return self.pop_ready(force=True)

    def fail_pending(self, exc: Exception) -> None:
        """Fail every queued request (server shutdown without drain).
        Settlement happens after the lock is released — done-callbacks
        run synchronously and may re-enter the scheduler."""
        drained: list[Request] = []
        with self._lock:
            for q in self._pending.values():
                while q:
                    drained.append(q.popleft())
        for req in drained:
            settle(req.future, exc=exc)
