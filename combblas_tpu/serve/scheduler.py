"""Admission control + flush policy: the backpressured front door.

A bounded pending queue with reject-with-retry-after admission (a full
queue REFUSES work instead of buffering unboundedly — the load-shedding
half of a serving stack), per-kind deadline-driven flushing (a batch
goes out when it fills its widest lane bucket OR its oldest request has
waited ``max_wait_s``), per-request timeouts, and error isolation: a
malformed root fails ITS future at admission and never contaminates a
batch.

Thread-safe; the api-layer worker loop drives ``pop_ready`` /
``next_deadline``. Everything here is host-side bookkeeping — no JAX in
this module.
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
import time
from collections import deque
from concurrent.futures import Future

from .. import obs
from ..obs.trace import RequestTrace
from .batcher import Request, expire, settle


def _bump(d: dict, kind: str, n: int = 1) -> None:
    """Per-kind counter bump (shared by Scheduler and Server)."""
    d[kind] = d.get(kind, 0) + n


class BackpressureError(RuntimeError):
    """Queue full: the caller should back off and retry.

    ``retry_after_s`` is the server's hint — one flush deadline, i.e.
    when capacity is next expected to free up.  ``tenant`` (round 14)
    NAMES the rejected tenant when the error came out of a
    multi-tenant pool — a fleet client must know WHOSE budget it blew,
    not just that some queue somewhere was full.
    """

    def __init__(self, depth: int, retry_after_s: float,
                 tenant: str | None = None):
        who = f"tenant {tenant!r}: " if tenant else ""
        super().__init__(
            f"{who}serve queue full ({depth} pending); retry after "
            f"{retry_after_s:.3f}s"
        )
        self.retry_after_s = retry_after_s
        self.tenant = tenant


class CircuitBreakerOpen(BackpressureError):
    """This kind's breaker is open: recent executions failed
    consecutively, so submits fast-fail instead of queueing work the
    engine will predictably burn a device lane on. A subclass of
    ``BackpressureError`` — retry-after semantics are identical, so
    callers with a backoff loop need no new handling."""

    def __init__(self, kind: str, retry_after_s: float,
                 tenant: str | None = None):
        who = f"tenant {tenant!r}: " if tenant else ""
        RuntimeError.__init__(
            self,
            f"{who}circuit breaker open for kind {kind!r}; retry after "
            f"{retry_after_s:.3f}s",
        )
        self.kind = kind
        self.retry_after_s = retry_after_s
        self.tenant = tenant


#: Circuit-breaker states (also the ``serve.breaker.state`` gauge
#: values: closed=0, half_open=1, open=2).
BREAKER_CLOSED = "closed"
BREAKER_HALF_OPEN = "half_open"
BREAKER_OPEN = "open"
_BREAKER_GAUGE = {BREAKER_CLOSED: 0, BREAKER_HALF_OPEN: 1, BREAKER_OPEN: 2}


class CircuitBreaker:
    """Consecutive-failure breaker for one query kind.

    CLOSED counts consecutive top-level batch failures; at
    ``threshold`` it OPENs: admissions fast-fail with
    ``CircuitBreakerOpen`` until ``cooldown_s`` elapses, then the next
    admission flips it HALF_OPEN (a probe is let through). The probe
    batch's outcome decides: success re-CLOSEs (cooldown resets),
    failure re-OPENs with the cooldown doubled (capped at
    ``cooldown_max_s``) — a persistently broken kind backs off
    exponentially instead of retrying at a fixed cadence.

    Failures are recorded at TOP-LEVEL batch granularity by the api
    worker (bisection-recovery sub-batches are not counted), so one
    poisoned request in an otherwise healthy engine cannot open the
    breaker. All methods take an explicit ``now`` for deterministic
    tests; thread-safe.
    """

    def __init__(self, threshold: int = 5, cooldown_s: float = 1.0,
                 cooldown_max_s: float = 30.0,
                 tenant: str | None = None):
        if threshold < 1:
            raise ValueError("breaker threshold must be >= 1")
        self.threshold = int(threshold)
        self.cooldown_s = float(cooldown_s)
        self.cooldown_max_s = float(cooldown_max_s)
        #: Owning tenant (round 14): rides every obs label this breaker
        #: emits, so a pool dashboard separates tenants' breaker state.
        #: ``None`` (the single-tenant default) adds no label — the
        #: pre-pool series names are unchanged.
        self.tenant = tenant
        self._lock = threading.Lock()
        self.state = BREAKER_CLOSED
        self.failures = 0  # consecutive, while CLOSED
        self.opened_at: float | None = None
        self._cooldown = self.cooldown_s
        self._probe_at: float | None = None  # half-open probe admitted
        self.opened_total = 0
        self.fast_fails = 0

    def _lab(self, kind: str) -> dict:
        """obs labels: ``kind`` always, ``tenant`` only when owned by a
        pool tenant (single-tenant series stay label-compatible)."""
        if self.tenant is None:
            return {"kind": kind}
        return {"kind": kind, "tenant": self.tenant}

    def admit(self, now: float, kind: str = "") -> bool:
        """May a submit of this kind be admitted right now? An OPEN
        breaker whose cooldown has elapsed flips HALF_OPEN here — the
        admitted request IS the probe, and it is the ONLY one: further
        submits fast-fail until the probe's batch outcome decides (or
        a full cooldown passes without an outcome — a probe that
        expired in queue must not wedge the breaker half-open
        forever)."""
        with self._lock:
            if self.state == BREAKER_OPEN:
                if now - self.opened_at >= self._cooldown:
                    self.state = BREAKER_HALF_OPEN
                    self._probe_at = now
                    obs.gauge("serve.breaker.state",
                              _BREAKER_GAUGE[self.state],
                              **self._lab(kind))
                    return True
                self.fast_fails += 1
                return False
            if self.state == BREAKER_HALF_OPEN:
                if (
                    self._probe_at is None
                    or now - self._probe_at >= self._cooldown
                ):
                    self._probe_at = now  # stale probe: re-probe
                    return True
                self.fast_fails += 1
                return False
            return True  # CLOSED

    def release_probe(self) -> None:
        """Give back a half-open probe slot whose request never made
        it into the queue (queue-full or close() raced the admit) —
        otherwise the kind stays fast-failing for a full cooldown with
        no probe actually in flight."""
        with self._lock:
            if self.state == BREAKER_HALF_OPEN:
                self._probe_at = None

    def retry_after(self, now: float) -> float:
        with self._lock:
            if self.state == BREAKER_OPEN and self.opened_at is not None:
                return max(0.0, self.opened_at + self._cooldown - now)
            if (
                self.state == BREAKER_HALF_OPEN
                and self._probe_at is not None
            ):
                # waiting on the outstanding probe's outcome
                return max(0.0, self._probe_at + self._cooldown - now)
            return 0.0

    def record_success(self, now: float, kind: str = "") -> None:
        closed_now = False
        with self._lock:
            self.failures = 0
            self._probe_at = None
            if self.state != BREAKER_CLOSED:
                self.state = BREAKER_CLOSED
                self._cooldown = self.cooldown_s
                closed_now = True
        if closed_now:  # gauge only on TRANSITION: the steady-state
            # healthy path (one record_success per batch) stays free
            obs.gauge("serve.breaker.state", 0, **self._lab(kind))

    def record_failure(self, now: float, kind: str = "") -> bool:
        """Record one top-level batch failure.  Returns True exactly
        when THIS call transitioned the breaker to OPEN — the flight
        recorder's ``breaker_open`` dump trigger."""
        opened = False  # did THIS call transition to OPEN?
        with self._lock:
            if self.state == BREAKER_HALF_OPEN:
                # the probe failed: back off harder
                self.state = BREAKER_OPEN
                self.opened_at = now
                self._probe_at = None
                self._cooldown = min(2 * self._cooldown,
                                     self.cooldown_max_s)
                self.opened_total += 1
                opened = True
            elif self.state == BREAKER_CLOSED:
                self.failures += 1
                if self.failures >= self.threshold:
                    self.state = BREAKER_OPEN
                    self.opened_at = now
                    self._cooldown = self.cooldown_s
                    self.opened_total += 1
                    opened = True
            else:  # OPEN: a straggler batch admitted pre-open failed —
                # refresh the clock, but it is NOT a new open transition
                self.opened_at = now
            state = self.state
        obs.gauge("serve.breaker.state", _BREAKER_GAUGE[state],
                  **self._lab(kind))
        if opened:
            obs.count("serve.breaker.opened", **self._lab(kind))
        return opened

    def describe(self, now: float) -> dict:
        with self._lock:
            return {
                "state": self.state,
                "consecutive_failures": self.failures,
                "opened_total": self.opened_total,
                "fast_fails": self.fast_fails,
                "cooldown_s": self._cooldown,
                "retry_after_s": (
                    max(0.0, self.opened_at + self._cooldown - now)
                    if self.state == BREAKER_OPEN else 0.0
                ),
            }


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Policy knobs for one server instance.

    ``lane_widths``: ascending shape buckets a flush may compile/execute
    under (every width here should be covered by ``warmup()`` so
    steady-state serving never traces). ``max_wait_s``: flush deadline —
    the latency a lonely request pays waiting for lane-mates;
    ``per_kind_max_wait`` overrides it per query kind. ``max_queue``
    bounds TOTAL pending requests across kinds (admission control).

    Resilience knobs: ``retry_budget`` is the number of FAILING
    executions one request may ride before its future fails. The
    default (``None``) is computed from the widest lane bucket as
    ``1 + ceil(log2(w_max))`` — exactly a full bisection (width 16:
    16→8→4→2→1 = 5), so one poison request always fails ALONE and its
    lane-mates survive regardless of configured widths. An explicit
    smaller value is the operator's bounded-work/fail-fast choice: a
    batch that exhausts it above width 1 fails innocents alongside the
    poison. ``breaker_threshold`` consecutive
    top-level batch failures open a kind's circuit breaker
    (``None``/0 disables breakers); an open breaker fast-fails submits
    for ``breaker_cooldown_s``, then a half-open probe decides —
    failure doubles the cooldown up to ``breaker_cooldown_max_s``.
    ``worker_backoff_s``/``worker_backoff_max_s`` bound the api
    worker's exponential error backoff (reset on success).
    """

    lane_widths: tuple[int, ...] = (1, 2, 4, 8, 16)
    max_queue: int = 1024
    max_wait_s: float = 0.01
    per_kind_max_wait: dict | None = None
    default_timeout_s: float | None = None
    retry_budget: int | None = None  # None -> 1 + ceil(log2(w_max))
    breaker_threshold: int | None = 5
    breaker_cooldown_s: float = 1.0
    breaker_cooldown_max_s: float = 30.0
    worker_backoff_s: float = 0.05
    worker_backoff_max_s: float = 2.0
    # -- write lane (docs/dynamic.md "Serving writes"): submit_update
    # admits edge mutations into a bounded DeltaBuffer (capacity
    # ``update_buffer``; full = reject with BackpressureError) and a
    # dedicated mutation thread merges a batch when ``update_flush``
    # ops have coalesced OR the oldest has waited ``update_max_delay_s``
    # — reads stay hot on the current version during the whole merge,
    # only the atomic swap takes the execution lock.
    # ``update_autostart=False`` disables the thread (deterministic
    # tests drive ``Server.pump_updates()`` instead).
    update_buffer: int = 4096
    update_flush: int = 64
    update_max_delay_s: float = 0.05
    update_autostart: bool = True
    # -- per-tenant SLO admission (round 14; docs/serving.md
    # "Multi-tenant pool & fleet").  ``slo_queue_budget`` rejects a
    # submit once THIS scheduler holds that many pending requests
    # (tighter than ``max_queue`` — the tenant's share of the pool, not
    # the pool's physical bound); ``slo_deadline_s`` caps every
    # admitted request's timeout at the tenant's deadline budget, so a
    # request that cannot be served inside the SLO expires instead of
    # occupying a lane late.  Both ``None`` (default) = disabled.
    slo_queue_budget: int | None = None
    slo_deadline_s: float | None = None
    # -- production observability (round 15; docs/observability.md
    # "Serving observability").  ``slo_target``/``slo_window_s``
    # parameterize the rolling-window error budget built whenever
    # ``slo_deadline_s`` is set (``serve/slo.py``).
    # ``flight_recorder`` keeps a bounded always-on ring of per-batch
    # stage events (``obs/recorder.py``) dumped as a schema-versioned
    # JSONL snapshot on worker error / breaker open / poisoned batch /
    # merge failure / SLO breach; False = the zero-cost opt-out (one
    # attribute read on the batch path).
    slo_target: float = 0.999
    slo_window_s: float = 60.0
    flight_recorder: bool = True
    flight_recorder_events: int = 256
    flight_recorder_dir: str | None = None
    flight_recorder_min_interval_s: float = 1.0
    # -- durability (round 16; docs/serving.md "Durability &
    # self-healing").  ``wal_dir`` names the directory holding the
    # write-ahead log + checkpoints (None resolves ``COMBBLAS_WAL``;
    # both unset = no durability, the zero-cost default: one attribute
    # read per write).  Every acknowledged ``submit_update`` appends to
    # the WAL before its future exists (``wal_fsync``:
    # arg > ``COMBBLAS_WAL_FSYNC`` > "always"), a background
    # checkpointer snapshots the served version every
    # ``checkpoint_every`` merges (arg > ``COMBBLAS_CHECKPOINT_EVERY``
    # > 8) or ``checkpoint_interval_s`` seconds (None = merge-count
    # only), atomically, OFF the execution lock, truncating the
    # replayed WAL prefix and retaining ``checkpoint_retain``
    # snapshots (arg > ``COMBBLAS_CHECKPOINT_RETAIN`` > 2).
    wal_dir: str | None = None
    wal_fsync: str | None = None
    checkpoint_every: int | None = None
    checkpoint_interval_s: float | None = None
    checkpoint_retain: int | None = None

    def __post_init__(self):
        if (
            not self.lane_widths
            or tuple(sorted(self.lane_widths)) != tuple(self.lane_widths)
            or self.lane_widths[0] < 1
        ):
            raise ValueError(
                "lane_widths must be ascending positive ints"
            )
        if self.retry_budget is None:
            # full-bisection budget for the widest configured bucket
            # (frozen dataclass: assign via object.__setattr__)
            object.__setattr__(
                self, "retry_budget",
                1 + max(0, int(self.lane_widths[-1]) - 1).bit_length(),
            )
        if self.retry_budget < 1:
            raise ValueError("retry_budget must be >= 1")
        if not (0 < self.worker_backoff_s <= self.worker_backoff_max_s):
            raise ValueError(
                "need 0 < worker_backoff_s <= worker_backoff_max_s"
            )
        if self.update_buffer < 1 or self.update_flush < 1:
            raise ValueError(
                "update_buffer and update_flush must be >= 1"
            )
        if self.update_max_delay_s <= 0:
            raise ValueError("update_max_delay_s must be > 0")
        if self.slo_queue_budget is not None and self.slo_queue_budget < 1:
            raise ValueError("slo_queue_budget must be >= 1")
        if self.slo_deadline_s is not None and self.slo_deadline_s <= 0:
            raise ValueError("slo_deadline_s must be > 0")
        if not (0.0 < self.slo_target < 1.0):
            raise ValueError("slo_target must be in (0, 1)")
        if self.slo_window_s <= 0:
            raise ValueError("slo_window_s must be > 0")
        if self.flight_recorder_events < 1:
            raise ValueError("flight_recorder_events must be >= 1")
        if self.flight_recorder_min_interval_s < 0:
            raise ValueError(
                "flight_recorder_min_interval_s must be >= 0"
            )
        if (
            self.checkpoint_every is not None
            and self.checkpoint_every < 1
        ):
            raise ValueError("checkpoint_every must be >= 1")
        if (
            self.checkpoint_interval_s is not None
            and self.checkpoint_interval_s <= 0
        ):
            raise ValueError("checkpoint_interval_s must be > 0")
        if (
            self.checkpoint_retain is not None
            and self.checkpoint_retain < 1
        ):
            raise ValueError("checkpoint_retain must be >= 1")

    def wait_for(self, kind: str) -> float:
        if self.per_kind_max_wait and kind in self.per_kind_max_wait:
            return self.per_kind_max_wait[kind]
        return self.max_wait_s


class Scheduler:
    """Pending-request store with admission control and flush policy."""

    def __init__(self, config: ServeConfig, nrows: int,
                 kinds: tuple[str, ...], tenant: str | None = None):
        self.config = config
        self.nrows = nrows
        self.kinds = kinds
        #: Owning tenant (round 14): named in every backpressure error
        #: and threaded through the obs labels below; ``None`` keeps
        #: the single-tenant label sets unchanged.
        self.tenant = tenant
        self._pending: dict[str, deque[Request]] = {
            k: deque() for k in kinds
        }
        self._rid = itertools.count()
        self._lock = threading.Lock()
        self._closed = False
        #: Shared ``serve.slo.ErrorBudget`` (assigned by the owning
        #: Server when ``config.slo_deadline_s`` is set): the queue
        #: sweep and the rejection paths record BAD dispositions here
        #: so the budget sees every user-visible failure, not just the
        #: executed ones.  None = no SLO accounting (one attribute
        #: read per site).
        self.slo = None
        #: Breach hook (assigned alongside ``slo``): called with the
        #: kind when a scheduler-side bad record BURNS THROUGH the
        #: budget — record() fires the transition exactly once per
        #: breach episode, so dropping its return here would swallow
        #: the flight-recorder dump whenever the crossing lands on a
        #: rejection/sweep instead of an execution failure.
        self.slo_breach = None
        self.rejected = 0  # backpressure only; breakers count separately
        self.submitted = 0
        # per-kind disposition counters (Server.stats()'s per_kind
        # table) — plain dicts bumped under _lock
        self.rejected_kind: dict[str, int] = {}
        self.invalid_kind: dict[str, int] = {}
        self.timeout_kind: dict[str, int] = {}
        self.breaker_rejected_kind: dict[str, int] = {}
        # per-kind circuit breakers (execution health -> admission
        # fast-fail); the api worker records batch outcomes into these
        self.breakers: dict[str, CircuitBreaker] = (
            {
                k: CircuitBreaker(
                    config.breaker_threshold,
                    config.breaker_cooldown_s,
                    config.breaker_cooldown_max_s,
                    tenant=tenant,
                )
                for k in kinds
            }
            if config.breaker_threshold else {}
        )

    def _lab(self, **labels) -> dict:
        """obs labels with the tenant attached when one owns this
        scheduler (see ``CircuitBreaker._lab``)."""
        if self.tenant is not None:
            labels["tenant"] = self.tenant
        return labels

    def _slo_bad(self, kind: str) -> None:
        """One scheduler-side bad SLO disposition; a budget-burn
        crossing fires the owning Server's breach hook (the
        flight-recorder dump — record() returns the transition exactly
        once per episode, so it must not be dropped here)."""
        if self.slo is not None and self.slo.record(False, kind=kind):
            if self.slo_breach is not None:
                self.slo_breach(kind)

    def close(self) -> None:
        """Refuse all further admissions, PERMANENTLY (set under the
        admission lock, so a submit racing ``Server.close`` either
        lands before the drain or raises — it can never be silently
        stranded)."""
        with self._lock:
            self._closed = True

    @property
    def closed(self) -> bool:
        with self._lock:
            return self._closed

    # -- admission ---------------------------------------------------------

    def depth(self) -> int:
        with self._lock:
            return sum(len(q) for q in self._pending.values())

    def submit(self, kind: str, root, timeout_s: float | None = None,
               now: float | None = None,
               trace_rid: int | str | None = None,
               trace=None) -> Future:
        """Admit one single-root query; returns its Future.

        Raises ``BackpressureError`` when the queue is full and
        ``ValueError`` for an unknown kind (caller bugs, not load). A
        MALFORMED ROOT is isolated instead: its future carries the
        ValueError and the request never enters a batch.

        ``trace_rid`` adopts an upstream sampling decision (round 18):
        a process-fleet router that already sampled a request forwards
        its rid over IPC, and the child-side scheduler traces it
        UNCONDITIONALLY under that rid — re-rolling the local sampler
        here would decorrelate the stitched trace's two halves.  The
        trace rides the Future as ``_combblas_trace`` so the IPC reply
        path can ship its stage marks home.

        ``trace`` adopts an upstream trace OBJECT (round 19): the net
        frontend opens (and holds) the trace at the socket, charges
        its ``net_accept``/``net_read`` stages, and hands the same
        object down so the scheduler's queue/assemble/execute/scatter
        marks land in one record — same-process stitching, no rid
        forwarding needed.  Mutually exclusive with ``trace_rid``.
        """
        if kind not in self._pending:
            raise ValueError(
                f"unknown query kind {kind!r}; engine serves {self.kinds}"
            )
        with self._lock:  # closed check FIRST: close semantics must not
            # depend on whether the root happened to be malformed
            if self._closed:
                raise RuntimeError(
                    "serve.Server is closed; no further admissions"
                )
        now = time.monotonic() if now is None else now
        fut: Future = Future()
        timeout_s = (
            timeout_s if timeout_s is not None
            else self.config.default_timeout_s
        )
        slo = self.config.slo_deadline_s
        if slo is not None:
            # SLO deadline budget: a request may never outlive the
            # tenant's deadline, whatever timeout it asked for — late
            # answers are as bad as no answers under an SLO
            timeout_s = slo if timeout_s is None else min(timeout_s, slo)
        deadline = None if timeout_s is None else now + timeout_s
        # error isolation: a bad root fails its OWN request, not a batch
        try:
            root_i = int(root)
            if root_i != root or not (0 <= root_i < self.nrows):
                raise ValueError(
                    f"root {root!r} outside [0, {self.nrows})"
                )
        except (TypeError, ValueError) as e:
            fut.set_exception(
                e if isinstance(e, ValueError) else ValueError(str(e))
            )
            with self._lock:
                _bump(self.invalid_kind, kind)
            obs.count(
                "serve.requests", **self._lab(kind=kind, status="invalid")
            )
            return fut
        breaker = self.breakers.get(kind)
        if breaker is not None and not breaker.admit(now, kind):
            # fast-fail OUTSIDE the queue lock: an open breaker is an
            # execution-health fact, not a queue-depth one
            with self._lock:
                _bump(self.breaker_rejected_kind, kind)
            obs.count("serve.breaker.fast_fail", **self._lab(kind=kind))
            # a fast-failed request is a user-visible failure under an
            # SLO (breach transitions reach the recorder via the hook)
            self._slo_bad(kind)
            raise CircuitBreakerOpen(
                kind, breaker.retry_after(now), tenant=self.tenant
            )
        try:
            with self._lock:
                if self._closed:  # re-check: close() may have raced
                    # the host-side validation above
                    raise RuntimeError(
                        "serve.Server is closed; no further admissions"
                    )
                d = sum(len(q) for q in self._pending.values())
                budget = self.config.max_queue
                if self.config.slo_queue_budget is not None:
                    # the tenant's queue-depth budget: its share of the
                    # pool, enforced tighter than the physical bound
                    budget = min(budget, self.config.slo_queue_budget)
                if d >= budget:
                    self.rejected += 1
                    _bump(self.rejected_kind, kind)
                    obs.count("serve.queue.rejected", **self._lab(kind=kind))
                    raise BackpressureError(
                        d, self.config.wait_for(kind), tenant=self.tenant
                    )
                req = Request(
                    rid=next(self._rid), kind=kind, root=root_i,
                    future=fut, submitted_at=now, deadline=deadline,
                )
                # deterministic-sampled per-request trace (round 15):
                # attached BEFORE the request becomes poppable — a
                # post-append attach could race the worker, whose pop
                # would then miss the early stage marks (or finish
                # before the trace exists, leaking it uncommitted).
                # Inside the admission lock only on success, so a
                # rejected submit never allocates one; obs.request_
                # trace is host-dict work (the queue-depth gauge below
                # sets the in-lock precedent), disabled obs = one call
                # + flag check.
                if trace is not None:
                    # round 19: adopt the transport's live trace —
                    # the frontend already rolled the sampler and
                    # charged its ingress stages; ride the future so
                    # worker/sweep settle paths find it as usual
                    req.trace = trace
                    fut._combblas_trace = trace
                elif trace_rid is None:
                    req.trace = obs.request_trace(
                        req.rid, kind=kind, tenant=self.tenant
                    )
                else:
                    # adopted upstream decision: trace unconditionally
                    # (the router already rolled the sampler) under the
                    # ROUTER's rid, so the stitched halves correlate
                    req.trace = RequestTrace(
                        trace_rid, "serve.request",
                        {
                            k: v
                            for k, v in (
                                ("kind", kind), ("tenant", self.tenant),
                            )
                            if v is not None
                        },
                    )
                    fut._combblas_trace = req.trace
                self._pending[kind].append(req)
                self.submitted += 1
                obs.gauge("serve.queue.depth", d + 1, **self._lab())
        except (BackpressureError, RuntimeError) as e:
            if breaker is not None:
                # this submit may have claimed the half-open probe
                # slot in admit() above; it never entered the queue,
                # so give the slot back (no-op unless half-open)
                breaker.release_probe()
            if isinstance(e, BackpressureError):
                self._slo_bad(kind)
            raise
        return fut

    # -- flush policy ------------------------------------------------------

    def _dispatch_by(self, kind: str, r: Request) -> float:
        """Latest time ``r`` should enter a batch: its kind's flush
        deadline, tightened for short per-request timeouts — a request
        whose timeout is under 2x the kind's max-wait dispatches at
        HALF its timeout budget (half for queueing, half for
        execution), instead of being slept past and expired in queue."""
        wait = self.config.wait_for(kind)
        if r.deadline is None:
            return r.submitted_at + wait
        budget = (r.deadline - r.submitted_at) / 2
        return r.submitted_at + min(wait, budget)

    def _kind_deadline(self, kind: str, q) -> float:
        """When this kind must flush: the earliest dispatch-by time of
        any queued request. An O(queue-depth) scan, bounded by
        ``max_queue`` (default 1024 — microseconds of host arithmetic
        next to a device batch); track incrementally if max_queue ever
        grows by orders of magnitude."""
        return min(self._dispatch_by(kind, r) for r in q)

    def next_deadline(self) -> float | None:
        """Absolute time of the earliest pending flush, or None when
        idle (see ``_kind_deadline`` for what counts as a deadline)."""
        with self._lock:
            deadlines = [
                self._kind_deadline(k, q)
                for k, q in self._pending.items() if q
            ]
        return min(deadlines) if deadlines else None

    def has_ready(self, now: float | None = None) -> bool:
        """True when some kind is flushable RIGHT NOW (full widest
        bucket or dispatch deadline reached) — the worker checks this
        under its wake lock before sleeping, closing the window where a
        burst's notify lands while no one is waiting."""
        now = time.monotonic() if now is None else now
        wmax = self.config.lane_widths[-1]
        with self._lock:
            return any(
                q and (
                    len(q) >= wmax or now >= self._kind_deadline(k, q)
                )
                for k, q in self._pending.items()
            )

    def pop_ready(self, now: float | None = None,
                  force: bool = False,
                  max_batches: int | None = None) -> list[list[Request]]:
        """Batches due for execution: a kind flushes when it can fill
        the widest lane bucket, when its oldest request has aged past
        the kind's flush deadline, or unconditionally under ``force``
        (drain/close). Expired requests are timed out here, before
        batching. Returns a list of per-kind request lists (each at most
        the widest bucket — a deep backlog flushes over several calls).

        ``max_batches`` (round 14) bounds how many batches one call may
        pop — the weighted-fair-queueing pump pops ONE batch per
        deficit charge so a saturated tenant drains in weighted shares
        instead of monopolizing the worker for its whole backlog; the
        dead-request sweep still covers every kind regardless.
        """
        now = time.monotonic() if now is None else now
        wmax = self.config.lane_widths[-1]
        out: list[list[Request]] = []
        timed_out: list[Request] = []
        with self._lock:
            for kind, q in self._pending.items():
                # full-queue sweep for DEAD requests — expired (even
                # BEHIND a fresh head) or client-cancelled: neither may
                # ride into a batch and waste a device lane or trigger
                # a premature flush; any() guards the rebuild off the
                # common all-live path. Expired requests are only
                # COLLECTED here — settling runs done-callbacks
                # synchronously, and a callback that re-enters submit()
                # would deadlock on this non-reentrant lock
                def dead(r):
                    return r.expired(now) or r.future.done()

                if any(dead(r) for r in q):
                    live = [r for r in q if not dead(r)]
                    for req in q:
                        if req.future.done():  # client cancel/settle
                            obs.count(
                                "serve.requests",
                                **self._lab(kind=kind, status="cancelled"),
                            )
                        elif req.expired(now):
                            timed_out.append(req)
                    q.clear()
                    q.extend(live)
                while q and (
                    force
                    or len(q) >= wmax
                    or now >= self._kind_deadline(kind, q)
                ):
                    if (
                        max_batches is not None
                        and len(out) >= max_batches
                    ):
                        break
                    take = min(len(q), wmax)
                    out.append([q.popleft() for _ in range(take)])
            obs.gauge(
                "serve.queue.depth",
                sum(len(q) for q in self._pending.values()),
                **self._lab(),
            )
        if timed_out:
            with self._lock:
                for req in timed_out:
                    _bump(self.timeout_kind, req.kind)
        for req in timed_out:  # settle OUTSIDE the lock (see above;
            # the per-kind bump already happened under it)
            if expire(req, "expired in queue"):
                self._slo_bad(req.kind)
        return out

    def drain(self) -> list[list[Request]]:
        """Everything still pending, as batches (close/shutdown path)."""
        return self.pop_ready(force=True)

    def fail_pending(self, exc: Exception) -> int:
        """Fail every queued request (server shutdown without drain).
        Settlement happens after the lock is released — done-callbacks
        run synchronously and may re-enter the scheduler.  Returns
        requests failed (the quarantine accounting, round 16)."""
        drained: list[Request] = []
        with self._lock:
            for q in self._pending.values():
                while q:
                    drained.append(q.popleft())
        for req in drained:
            settle(req.future, exc=exc)
            if req.trace is not None:  # abandoned reads still close
                # their sampled trace (the write lane's _stop_mutator
                # convention) — sampled==committed+dropped must hold
                req.trace.finish(status="aborted", stage="settle")
        return len(drained)


class DeficitRoundRobin:
    """Weighted fair queueing across tenants (round 14): classic
    deficit round robin over the tenants' own bounded queues.

    Each scheduling ROUND grants every backlogged tenant
    ``quantum x weight`` deficit credit and yields the tenants in
    rotation order (the start position advances per round, so no
    tenant enjoys a systematic first-mover advantage); the pump then
    serves a tenant while its ``balance`` stays positive, CHARGING the
    actual request count of each executed batch (post-charge: a batch
    may overdraw the balance by at most one bucket width — the
    overdraft carries into the next round, so long-run served shares
    converge to the weights).  A tenant whose backlog EMPTIES has its
    deficit reset (no banking: an idle tenant cannot hoard credit and
    later burst past its weight — the textbook DRR rule).

    Write-lane fairness rides the same meter: the pool pump charges a
    tenant's merge cost (ops folded) against the same deficit, so a
    mutation-heavy tenant spends its share on writes instead of
    starving everyone else's reads.

    Deterministic (no clocks, no randomness) and thread-safe; the obs
    series are ``serve.wfq.rounds``, ``serve.wfq.served{tenant}`` and
    ``serve.wfq.deficit{tenant}``.
    """

    def __init__(self, quantum: int | None = None):
        from ..tuner import config as tuner_config

        self.quantum = tuner_config.pool_quantum(quantum)
        self._lock = threading.Lock()
        self._weights: dict[str, float] = {}
        self._deficit: dict[str, float] = {}
        self._cursor = 0
        self.rounds = 0
        self.served: dict[str, int] = {}

    def add(self, tenant: str, weight: float = 1.0) -> None:
        if weight <= 0:
            raise ValueError(
                f"tenant {tenant!r} needs a positive WFQ weight, "
                f"got {weight}"
            )
        with self._lock:
            self._weights[tenant] = float(weight)
            self._deficit.setdefault(tenant, 0.0)

    def remove(self, tenant: str) -> None:
        with self._lock:
            self._weights.pop(tenant, None)
            self._deficit.pop(tenant, None)
            self.served.pop(tenant, None)

    def prune(self, live) -> list[str]:
        """Drop every tenant NOT in ``live`` (the pool pump calls this
        with the current tenant list): add/remove churn must not leak
        weights/deficit/served entries — or their obs label space —
        for dead tenant names forever.  Returns the pruned names so
        the caller can prune the metrics registry's ``tenant=`` label
        space in the same breath (``obs.prune_labels``)."""
        live = set(live)
        removed = []
        with self._lock:
            for t in [x for x in self._weights if x not in live]:
                self._weights.pop(t, None)
                self._deficit.pop(t, None)
                self.served.pop(t, None)
                removed.append(t)
        return removed

    def set_weight(self, tenant: str, weight: float) -> None:
        self.add(tenant, weight)

    def balance(self, tenant: str) -> float:
        with self._lock:
            return self._deficit.get(tenant, 0.0)

    def round(self, backlogged) -> list[str]:
        """One DRR round: grant ``quantum x weight`` to every
        backlogged tenant, reset idle tenants' deficit, and return the
        backlogged tenants in this round's rotation order."""
        with self._lock:
            names = list(self._weights)
            live = {t for t in backlogged if t in self._weights}
            for t in names:
                if t in live:
                    self._deficit[t] += self.quantum * self._weights[t]
                else:
                    self._deficit[t] = 0.0  # no banking while idle
            if not names:
                return []
            start = self._cursor % len(names)
            self._cursor += 1
            order = [
                t for t in names[start:] + names[:start] if t in live
            ]
            self.rounds += 1
            # deficit SNAPSHOT under the lock: a concurrent remove()
            # between release and the gauge loop must not KeyError
            snap = {t: self._deficit[t] for t in order}
        if obs.ENABLED:
            obs.count("serve.wfq.rounds")
            for t, v in snap.items():
                obs.gauge("serve.wfq.deficit", v, tenant=t)
        return order

    def charge(self, tenant: str, cost: float) -> None:
        """Spend ``cost`` (requests served or write ops merged) from
        the tenant's balance — may overdraw (see class docstring)."""
        with self._lock:
            if tenant in self._deficit:
                self._deficit[tenant] -= cost
            self.served[tenant] = (
                self.served.get(tenant, 0) + int(cost)
            )
        if obs.ENABLED:
            obs.count("serve.wfq.served", cost, tenant=tenant)

    def describe(self) -> dict:
        with self._lock:
            return {
                "quantum": self.quantum,
                "rounds": self.rounds,
                "weights": dict(self._weights),
                "deficit": {
                    k: round(v, 3) for k, v in self._deficit.items()
                },
                "served": dict(self.served),
            }
