"""Replica subprocess entry point (round 17, the process fleet).

``python -m combblas_tpu.serve._procworker --fd N`` is what
``ProcessFleet`` spawns: one OS process hosting one ``Server`` with
its OWN JAX runtime (the parent exports ``JAX_PLATFORMS=cpu`` and a
per-replica ``XLA_FLAGS --xla_force_host_platform_device_count``
before exec, so the child's mesh is genuinely its own — no shared
exec lock, no cross-process XLA rendezvous: the deadlock that forces
the thread fleet to serialize replicas does not exist here).

Protocol (``serve/ipc.py`` framing) — the parent sends requests
``{"id": n, "op": ..., ...}``; the child replies ``{"id": n, "ok":
true, "result": ...}`` or ``{"id": n, "ok": false, "etype": ...,
"error": ...}``.  ``submit``/``submit_update`` dispatch to the server
and reply from the future's done-callback, so the receive loop never
blocks on device execution (requests pipeline; the server's own
scheduler provides the queue).  Unsolicited ``{"hb": {...}}``
heartbeats carry queue depth, health, and the WAL frontier on a fixed
interval — the parent's liveness signal that distinguishes a WEDGED
process (SIGSTOP: alive but silent) from a busy one.

Graph payloads never cross the socket: the child boots from a
``save_version`` checkpoint path (or ``recover=True`` over the
durability dir), and fan-out arrives as ``swap_from_checkpoint``
naming a spool file on disk.
"""

from __future__ import annotations

import argparse
import os
import socket
import sys
import threading
import time
import traceback

# The parent pins the child's runtime through env BEFORE exec; these
# defaults only matter for hand-run workers.  Both must be set before
# jax is imported anywhere below.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "--xla_force_host_platform_device_count" not in os.environ.get(
    "XLA_FLAGS", ""
):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    )

# obs is import-light (no jax at module level) and reads COMBBLAS_OBS
# — which the parent pinned into our env — at import time, so the
# child's telemetry armed/unarmed state mirrors the router's.
from .. import obs  # noqa: E402


def _cfg_from_json(d: dict):
    """Rebuild a ServeConfig from the parent's dataclasses.asdict
    payload (tuples arrive as lists)."""
    from .scheduler import ServeConfig

    kw = dict(d or {})
    if "lane_widths" in kw and kw["lane_widths"] is not None:
        kw["lane_widths"] = tuple(kw["lane_widths"])
    return ServeConfig(**kw)


class ProcWorker:
    """The child-side dispatcher: one Server, one channel."""

    def __init__(self, channel, hb_interval_s: float = 0.25,
                 metrics_interval_s: float = 1.0):
        self.ch = channel
        self.srv = None
        self.grid = None
        self.hb_interval_s = hb_interval_s
        self.metrics_interval_s = metrics_interval_s
        self._last_snap_t = 0.0
        self._hb_stop = threading.Event()
        self._stop = False

    # -- replies -----------------------------------------------------------

    def _reply(self, rid, result=None, exc: Exception | None = None,
               trace: dict | None = None):
        from .ipc import ChannelClosed

        try:
            if exc is None:
                msg = {"id": rid, "ok": True, "result": result}
            else:
                msg = {
                    "id": rid, "ok": False,
                    "etype": type(exc).__name__,
                    "error": str(exc),
                    "retry_after_s": getattr(exc, "retry_after_s",
                                             None),
                }
            if trace is not None:
                # completed child-half stage marks, riding the reply's
                # JSON head home for router-side stitching (round 18)
                msg["trace"] = trace
            self.ch.send(msg)
        except ChannelClosed:
            # the parent died: nothing to report to; the main loop's
            # next recv sees the same closure and exits
            pass

    def _reply_from_future(self, rid, fut, trace=None):
        def _done(f):
            rec = None
            if trace is not None:
                # finish() is idempotent first-wins: the scatter path
                # also finishes committed traces, but the reply must
                # ship COMPLETE marks, and settle order (future first,
                # trace second) means we close the tail ourselves
                trace.finish(
                    status="ok" if f.exception() is None else "error",
                    stage="scatter",
                )
                rec = trace.record()
            if f.exception() is None:
                self._reply(rid, result=f.result(), trace=rec)
            else:
                self._reply(rid, exc=f.exception(), trace=rec)

        fut.add_done_callback(_done)

    # -- heartbeat ---------------------------------------------------------

    def _hb_loop(self):
        from .ipc import ChannelClosed

        while not self._hb_stop.wait(self.hb_interval_s):
            srv = self.srv
            if srv is None:
                continue
            hb = {
                "t": time.time(),
                "pid": os.getpid(),
                "depth": srv.scheduler.depth(),
                "serving": srv.is_serving(),
                "worker_errors": srv.worker_errors,
                "graph_version": srv.engine.version_id,
                "wal_frontier": (
                    srv._wal_frontier
                    if srv._wal is not None else None
                ),
                "updates_pending": (
                    srv._upd_buffer.depth()
                    if srv._upd_buffer is not None else 0
                ),
            }
            if obs.ENABLED:
                # metrics federation (round 18): piggyback a compact
                # registry snapshot — the aggregate() wire shape — on
                # the liveness channel at most every
                # metrics_interval_s; the supervisor folds it into the
                # fleet scrape with a replica= label
                now = time.monotonic()
                if now - self._last_snap_t >= self.metrics_interval_s:
                    self._last_snap_t = now
                    try:
                        obs.count("serve.procfleet.hb_snapshots")
                        hb["metrics"] = obs.metrics_snapshot()
                    except Exception:
                        pass  # a broken provider must not stop
                        # heartbeats — liveness outranks telemetry
            try:
                self.ch.send({"hb": hb})
            except ChannelClosed:
                return

    # -- ops ---------------------------------------------------------------

    def _op_boot(self, m: dict) -> dict:
        from .api import Server
        from .engine import GraphEngine
        from ..parallel.grid import Grid
        from ..utils import checkpoint

        pr, pc = m["grid"]
        self.grid = Grid.make(int(pr), int(pc))
        kinds = tuple(m["kinds"]) if m.get("kinds") else None
        cfg = _cfg_from_json(m.get("config"))
        home = bool(m.get("home", False))
        #: durability dir — only the HOME attaches the WAL to it; a
        #: non-home recover boot still READS it (snapshot + suffix)
        wal_dir = m.get("wal_dir")
        tenant = m.get("tenant") or f"proc{os.getpid()}"
        import dataclasses

        if m.get("recover"):
            # respawn / recovery boot: latest snapshot + WAL-suffix
            # replay — every acknowledged write, the same lineage
            if home:
                cfg = dataclasses.replace(cfg, wal_dir=wal_dir)
                self.srv = Server.from_recovery(
                    self.grid, cfg, kinds=kinds, tenant=tenant
                )
            else:
                from ..dynamic import wal as dyn_wal

                cfg = dataclasses.replace(cfg, wal_dir="off")
                v = dyn_wal.recover(wal_dir, self.grid, kinds=kinds)
                eng = GraphEngine(self.grid, version=v, kinds=kinds)
                self.srv = Server(eng, cfg, tenant=tenant)
        else:
            cfg = dataclasses.replace(
                cfg,
                wal_dir=(wal_dir if home and wal_dir is not None
                         else "off"),
            )
            v = checkpoint.load_version(
                m["ckpt"], self.grid, writable=home
            )
            eng = GraphEngine(self.grid, version=v, kinds=kinds)
            self.srv = Server(eng, cfg, tenant=tenant)
        self.srv.start()
        self.hb_interval_s = float(
            m.get("hb_interval_s", self.hb_interval_s)
        )
        self.metrics_interval_s = float(
            m.get("metrics_interval_s", self.metrics_interval_s)
        )
        # warm BEFORE taking traffic: with the shared plan store
        # (COMBBLAS_PLAN_STORE in the inherited env) populated, the
        # remembered lanes replay — the parent asserts zero
        # post-warmup retraces over IPC (trace_mark/retraces_since)
        warmed = {}
        if m.get("warmup", True):
            try:
                warmed = self.srv.warmup()
            except Exception as e:
                warmed = {"error": repr(e)}
        threading.Thread(
            target=self._hb_loop, name="combblas-proc-hb", daemon=True
        ).start()
        return {
            "pid": os.getpid(),
            "devices": self._device_count(),
            "warmed": {f"{k}": w for k, w in warmed.items()},
            "graph_version": self.srv.engine.version_id,
            "durable": self.srv.durable,
        }

    @staticmethod
    def _device_count() -> int:
        import jax

        return len(jax.devices())

    def dispatch(self, m: dict) -> bool:
        """Handle one request; returns False when the loop should
        exit (close)."""
        rid = m.get("id")
        op = m.get("op")
        try:
            if op == "boot":
                self._reply(rid, result=self._op_boot(m))
            elif op == "ping":
                self._reply(rid, result={"pong": True,
                                         "t": time.time()})
            elif op == "submit":
                fut = self.srv.submit(
                    m["kind"], m["root"],
                    timeout_s=m.get("timeout_s"),
                    trace_rid=m.get("trace"),
                )
                self._reply_from_future(
                    rid, fut,
                    trace=getattr(fut, "_combblas_trace", None),
                )
            elif op == "submit_update":
                ops = [tuple(o) for o in m["ops"]]
                fut = self.srv.submit_update(ops)
                self._reply_from_future(rid, fut)
            elif op == "spool_version":
                # fan-out source: snapshot the CURRENT version to the
                # spool path (atomic tmp+replace inside save_version);
                # sibling replicas swap from the file, not the wire
                from ..utils import checkpoint

                checkpoint.save_version(
                    m["path"], self.srv.engine.version
                )
                self._reply(rid, result={
                    "path": m["path"],
                    "version": self.srv.engine.version_id,
                })
            elif op == "swap_from_checkpoint":
                from ..utils import checkpoint

                v = checkpoint.load_version(
                    m["path"], self.grid, writable=False
                )
                res = self.srv.swap_graph(v)
                self._reply(rid, result=res)
            elif op == "promote":
                self._reply(rid, result=self._op_promote(m))
            elif op == "warmup":
                w = self.srv.warmup(
                    widths=m.get("widths"), kinds=(
                        tuple(m["kinds"]) if m.get("kinds") else None
                    ),
                )
                self._reply(rid, result={f"{k}": v
                                         for k, v in w.items()})
            elif op == "trace_mark":
                self._reply(rid, result={
                    "mark": self.srv.engine.trace_mark()
                })
            elif op == "retraces_since":
                self._reply(rid, result={
                    "retraces": self.srv.engine.retraces_since(
                        int(m["mark"])
                    )
                })
            elif op == "health":
                self._reply(rid, result=self.srv.health())
            elif op == "stats":
                self._reply(rid, result=self.srv.stats())
            elif op == "checkpoint_now":
                self._reply(rid, result=self.srv.checkpoint_now(
                    reason=m.get("reason", "manual")
                ))
            elif op == "close":
                self._hb_stop.set()
                if self.srv is not None:
                    self.srv.close(
                        drain=bool(m.get("drain", True)),
                        timeout=float(m.get("timeout", 30.0)),
                    )
                self._reply(rid, result={"closed": True})
                return False
            else:
                self._reply(rid, exc=ValueError(
                    f"unknown ipc op {op!r}"
                ))
        except Exception as e:
            # a failed op fails ITS request, never the worker: the
            # parent decides whether the error is fatal (quarantine)
            # or per-request (spill/retry)
            self._reply(rid, exc=e)
        return True

    def _op_promote(self, m: dict) -> dict:
        """Dead-home failover, child side: swap to the WAL frontier
        (``recover`` = snapshot + full suffix replay — exactly every
        acknowledged write), re-attach the write lane, re-warm."""
        from ..dynamic import wal as dyn_wal

        wal_dir = m["wal_dir"]
        v = dyn_wal.recover(
            wal_dir, self.grid, kinds=self.srv.engine.kinds()
        )
        self.srv.swap_graph(v)
        self.srv.attach_durability(wal_dir)
        try:
            self.srv.warmup()
        except Exception:
            pass  # warm-start is best effort; serving is not
        return {
            "wal_frontier": self.srv._wal_frontier,
            "graph_version": self.srv.engine.version_id,
        }

    def run(self) -> None:
        import socket as _socket

        while not self._stop:
            try:
                m = self.ch.recv(timeout=1.0)
            except _socket.timeout:
                continue
            except Exception:
                # ChannelClosed or an undecodable frame: the parent
                # is gone or corrupt — exit (the OS reaps us)
                break
            if "hb" in m:
                continue  # parent never heartbeats today; tolerate
            if not self.dispatch(m):
                break
        self._hb_stop.set()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--fd", type=int, required=True,
                    help="inherited socketpair fd (pass_fds)")
    ap.add_argument("--hb-interval-s", type=float, default=0.25)
    args = ap.parse_args(argv)
    sock = socket.socket(fileno=args.fd)
    from .ipc import Channel

    worker = ProcWorker(
        Channel(sock, peer="parent"), hb_interval_s=args.hb_interval_s
    )
    try:
        worker.run()
    except Exception:
        traceback.print_exc(file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
