"""EnginePool — many resident graphs behind one device (round 14).

Every serve capability before this round assumed ONE ``Server``, ONE
graph, ONE worker thread.  The pool takes the lane horizontal: many
tenants' graphs resident behind one device, one worker thread arbitrated
by weighted fair queueing, per-tenant everything (queues, SLOs, plan
caches, circuit breakers, fault injectors), and a byte-accounted LRU
that evicts cold tenants' DEVICE state while retaining the host inputs —
a re-admitted tenant is a REBUILD, not a reload from nowhere.

Three layers:

* **EnginePool** — tenant → ``GraphEngine`` routing plus residency.
  ``add_tenant`` registers the host COO (and every ``from_coo`` knob)
  and builds the engine; ``admit``/``evict`` move a tenant's device
  state in and out under the ``byte_budget``
  (``COMBBLAS_POOL_BYTE_BUDGET``; 0 = unbounded). Eviction drops the
  engine (its ELL buckets, twins, feature table — everything
  ``GraphVersion.device_bytes`` counts) but keeps the tenant's
  ``Server`` shell alive: queues, breakers, fault rules, write buffers
  and counters all survive, and a later admit rebuilds the engine
  BIT-EXACTLY from the retained host arrays (``from_coo`` is
  deterministic — ``to_host_coo()`` round-trips equal, the tested
  contract; eviction refreshes the rebuild source from the CURRENT
  version's host COO, so acknowledged writes survive the cycle). A
  rebuilt engine's plan cache is cold: re-admission pays its warmup
  again, which is exactly the rebuild-not-reload trade.
* **Per-tenant serving state** — each tenant wraps its engine in a
  WORKER-LESS ``Server`` (the PR-6/PR-9 machinery generalizes per
  tenant for free): its own bounded queue + SLO admission
  (``ServeConfig.slo_queue_budget`` / ``slo_deadline_s`` — rejections
  NAME the tenant), its own per-kind circuit breakers (tenant A's
  poison can never trip tenant B's breaker), its own ``FaultInjector``
  and its own write-lane ``DeltaBuffer``.
* **PoolServer** — the one worker thread that owns the device,
  arbitrating across tenants with ``scheduler.DeficitRoundRobin``:
  each round grants ``quantum x weight`` credit, read batches and
  write merges CHARGE the same meter (write-lane fairness — a
  mutation-heavy tenant spends its own share, it cannot starve other
  tenants' reads), and ``pop_ready(max_batches=1)`` keeps a saturated
  tenant from monopolizing the worker for its whole backlog.

Usage::

    pool = EnginePool(grid, byte_budget=512 << 20)
    pool.add_tenant("acme", rows_a, cols_a, n, weight=3.0)
    pool.add_tenant("bob", rows_b, cols_b, n)
    with pool.serve() as psrv:
        psrv.warmup()
        f = psrv.submit("acme", "bfs", root=7)
        print(f.result()["levels"][:10])
"""

from __future__ import annotations

import threading
import time
import traceback
import sys

from .. import obs
from .scheduler import DeficitRoundRobin, ServeConfig

#: Fixed wake-poll ceiling of the pool worker when only update-lane
#: deadlines are pending (their exact due time is cheap to compute, so
#: this is a backstop, not a cadence).
_IDLE_WAIT_S = 0.25


class _Tenant:
    """One tenant's registration: host build inputs (retained — the
    rebuild side of evict/admit), the resident engine (or None while
    evicted), and the always-alive Server shell."""

    def __init__(self, name: str, weight: float, build_args: dict,
                 config: ServeConfig):
        self.name = name
        self.weight = float(weight)
        self.build_args = build_args  # host arrays + from_coo knobs
        self.config = config
        self.engine = None            # resident GraphEngine or None
        self.server = None            # worker-less Server (persistent)
        self.busy = False             # a batch of this tenant is on
        #                               the device right now (evict
        #                               must not pull state mid-batch)
        self.admits = 0
        self.evictions = 0
        self.last_used = 0.0          # LRU clock (monotonic)
        self.device_bytes = 0         # accounted at admit/swap
        #: Serializes this tenant's engine BUILD (held outside the
        #: pool lock — one tenant's rebuild must not stall the pool's
        #: whole front door).
        self.build_lock = threading.Lock()


class EnginePool:
    """Tenant → engine routing with byte-accounted LRU residency."""

    def __init__(self, grid, byte_budget: int | None = None,
                 config: ServeConfig | None = None):
        from ..tuner import config as tuner_config

        self.grid = grid
        #: Resident-device-byte budget (0 = unbounded). Admitting past
        #: it evicts least-recently-used idle tenants first.
        self.byte_budget = tuner_config.pool_byte_budget(byte_budget)
        self.default_config = config or ServeConfig()
        self._lock = threading.RLock()
        self._tenants: dict[str, _Tenant] = {}
        self.over_budget = 0  # admits that could not evict under budget
        # ONE execution stream across tenants: every tenant engine
        # shares this lock (installed at admit), so a caller-thread
        # warmup() can never launch a collective program concurrently
        # with the pool worker's batch on the same device mesh —
        # concurrent SPMD launches interleave XLA's collective
        # rendezvous and deadlock (see FleetRouter, same hazard).
        self._device_lock = threading.RLock()

    # -- registration ------------------------------------------------------

    def add_tenant(self, name: str, rows, cols, nrows: int,
                   ncols: int | None = None, *, weight: float = 1.0,
                   config: ServeConfig | None = None,
                   resident: bool = True, **from_coo_kw) -> None:
        """Register a tenant graph. The host arrays (and every
        ``GraphEngine.from_coo`` keyword) are RETAINED for the
        eviction/re-admission cycle; ``resident=True`` builds and
        admits the engine now, ``False`` defers to first use."""
        if weight <= 0:
            raise ValueError(
                f"tenant {name!r} needs a positive weight, got {weight}"
            )
        config = config or self.default_config
        if config.update_autostart or config.wal_dir is None:
            # the POOL worker owns every tenant's write lane (merges
            # charge the WFQ meter); a per-tenant mutation thread
            # would merge outside the fairness arbiter.  An UNSET
            # wal_dir is pinned to "off" (round 16): N tenants each
            # resolving one ambient COMBBLAS_WAL would fight over a
            # single log/snapshot lineage — pool durability must be
            # an EXPLICIT per-tenant dir on the tenant's config
            import dataclasses

            config = dataclasses.replace(
                config, update_autostart=False,
                wal_dir="off" if config.wal_dir is None
                else config.wal_dir,
            )
        with self._lock:
            if name in self._tenants:
                raise ValueError(f"tenant {name!r} already registered")
            t = _Tenant(
                name, weight,
                dict(rows=rows, cols=cols, nrows=int(nrows),
                     ncols=ncols, **from_coo_kw),
                config,
            )
            self._tenants[name] = t
        if resident:
            self.admit(name)

    def remove_tenant(self, name: str) -> None:
        """Drop a tenant entirely (device AND host state). Its server
        shell refuses further admissions, and pending READS and
        buffered WRITES both fail (a removed tenant must never strand
        a future)."""
        with self._lock:
            t = self._tenants.pop(name, None)
        if t is not None and t.server is not None:
            t.server.scheduler.close()
            t.server.scheduler.fail_pending(
                RuntimeError(f"tenant {name!r} removed from pool")
            )
            # abort the write lane too: buffered ops + their futures
            # (the never-started-mutator path of the server's close)
            t.server._stop_mutator(drain=False, timeout=5.0)
            t.engine = None
            t.server.engine = None
        if t is not None:
            # label-space hygiene (round 15): the removed tenant's
            # labeled registry series (queue depth, requests, breaker,
            # WFQ, pool counters) must not live — in memory AND on the
            # scrape surface — forever; the WFQ state prunes in the
            # pump, the registry prunes here, at the churn point
            obs.prune_labels(tenant=name)
        self._gauge_residency()

    def tenant_names(self) -> list[str]:
        with self._lock:
            return list(self._tenants)

    def _get(self, name: str) -> _Tenant:
        with self._lock:
            t = self._tenants.get(name)
        if t is None:
            raise ValueError(
                f"unknown tenant {name!r}; pool serves "
                f"{sorted(self._tenants)}"
            )
        return t

    def _peek(self, name: str) -> "_Tenant | None":
        """Tolerant lookup for callers iterating a NAME SNAPSHOT
        (pump / deadline scans / stats): a tenant removed between the
        snapshot and the lookup is skipped, never raised on — a
        ``remove_tenant`` racing the worker's idle path must not kill
        the worker thread."""
        with self._lock:
            return self._tenants.get(name)

    # -- residency ---------------------------------------------------------

    def engine(self, name: str):
        """The tenant's resident engine (admitting it if evicted) —
        the tenant → GraphEngine route. Touches the LRU clock."""
        return self.admit(name)

    def server(self, name: str):
        """The tenant's worker-less ``Server`` shell (queues, breaker,
        faults, write buffer). Exists from first admit onward, engine
        resident or not."""
        t = self._get(name)
        with self._lock:
            if t.server is not None:
                return t.server
        self.admit(name)
        return t.server

    def admit(self, name: str):
        """Ensure the tenant's device state is resident: build the
        engine from the retained host inputs if evicted, evicting
        least-recently-used idle tenants while the pool sits over its
        byte budget. Returns the engine."""
        t = self._get(name)
        with self._lock:
            t.last_used = time.monotonic()
            if t.engine is not None:
                return t.engine
        return self._build_and_install(t)

    def claim(self, name: str):
        """Admit AND mark busy in one atomic step (the pump's
        pre-batch claim): once this returns, the LRU sweep cannot pull
        the engine out from under the caller's device work — a plain
        ``admit`` followed by ``busy = True`` leaves a window where a
        concurrent admit's budget sweep sees an idle tenant and
        evicts the engine mid-dereference. Pair with ``release``."""
        t = self._get(name)
        while True:
            with self._lock:
                if t.engine is not None:
                    t.busy = True
                    t.last_used = time.monotonic()
                    return t.engine
            self._build_and_install(t)

    def release(self, name: str) -> None:
        with self._lock:
            t = self._tenants.get(name)
            if t is not None:
                t.busy = False

    def _build_and_install(self, t: _Tenant):
        """(Re)build the tenant's engine OUTSIDE the pool lock — one
        tenant's seconds-long rebuild must not stall every other
        tenant's admission/stats path — then install and account under
        it. ``build_lock`` serializes racing builders of the SAME
        tenant (the loser returns the winner's engine)."""
        from .api import Server
        from .engine import GraphEngine

        with t.build_lock:
            with self._lock:
                if t.engine is not None:  # a racing admit built it
                    return t.engine
            # host bucket pass + device uploads, pool lock NOT held:
            # uploads concurrent with the worker's execution are safe
            # (the dynamic lane's off-lock merge precedent) — only
            # collective LAUNCHES need the shared device-stream lock
            t0 = time.perf_counter()
            engine = GraphEngine.from_coo(self.grid, **t.build_args)
            engine._exec_lock = self._device_lock  # one device stream
            nbytes = engine.version.device_bytes()
            with self._lock:
                t.engine = engine
                t.device_bytes = nbytes
                t.admits += 1
                t.last_used = time.monotonic()
                if t.server is None:
                    t.server = Server(engine, t.config, tenant=t.name)
                else:
                    # the shell survives eviction: reattach the rebuilt
                    # engine under its queues/breakers/fault rules
                    t.server.engine = engine
                obs.count("serve.pool.admits", tenant=t.name)
                obs.observe(
                    "serve.pool.rebuild_s", time.perf_counter() - t0
                )
                self._evict_to_budget(protect=t)
            self._gauge_residency()
            return engine

    def evict(self, name: str, force: bool = False) -> bool:
        """Drop one tenant's device state (host inputs + server shell
        retained). Refuses (returns False) when the tenant is busy or
        has pending work, unless ``force=True`` — forced eviction of a
        tenant with queued requests just means its next pump pays a
        rebuild first."""
        t = self._get(name)
        with self._lock:
            return self._evict_locked(t, force)

    def _idle(self, t: _Tenant) -> bool:
        """No batch on the device, no queued reads, no buffered
        writes — the only tenants the LRU sweep may cold-evict."""
        if t.busy:
            return False
        if t.server is None:
            return True
        if t.server.scheduler.depth() > 0:
            return False
        b = t.server._upd_buffer
        return b is None or b.depth() == 0

    def _evict_locked(self, t: _Tenant, force: bool = False) -> bool:
        if t.engine is None:
            return False
        if t.busy:
            return False  # never pull device state mid-batch
        if not force and not self._idle(t):
            return False
        v = t.engine.version
        if v.host_coo is not None:
            # merged mutations must survive the evict/re-admit cycle:
            # the rebuild source becomes the CURRENT version's retained
            # host COO (deduped — from_coo's re-dedup is the identity
            # on it), not the registration-time arrays, or every
            # acknowledged write would silently vanish at re-admission
            rows, cols, _nc = v.host_coo
            t.build_args["rows"] = rows
            t.build_args["cols"] = cols
            if v.host_weights is not None or "weights" in t.build_args:
                t.build_args["weights"] = v.host_weights
        t.engine = None
        if t.server is not None:
            t.server.engine = None
        t.device_bytes = 0
        t.evictions += 1
        obs.count("serve.pool.evictions", tenant=t.name)
        self._gauge_residency()
        return True

    def _evict_to_budget(self, protect: _Tenant) -> None:
        """LRU sweep (caller holds the lock): evict idle tenants,
        coldest first, until resident bytes fit the budget. The tenant
        being admitted is never a victim; if nothing else is evictable
        the pool runs over budget (counted) rather than refusing to
        serve."""
        if not self.byte_budget:
            return
        while self._resident_bytes_locked() > self.byte_budget:
            victims = sorted(
                (
                    x for x in self._tenants.values()
                    if x is not protect and x.engine is not None
                    and self._idle(x)
                ),
                key=lambda x: x.last_used,
            )
            if not victims:
                self.over_budget += 1
                obs.count("serve.pool.over_budget")
                return
            self._evict_locked(victims[0])

    def _resident_bytes_locked(self) -> int:
        return sum(
            t.device_bytes for t in self._tenants.values()
            if t.engine is not None
        )

    def resident_bytes(self) -> int:
        """Total device bytes of resident tenant versions (the
        ``serve.pool.resident_bytes`` gauge)."""
        with self._lock:
            return self._resident_bytes_locked()

    def _gauge_residency(self) -> None:
        if obs.ENABLED:
            with self._lock:
                obs.gauge(
                    "serve.pool.resident_bytes",
                    self._resident_bytes_locked(),
                )
                obs.gauge(
                    "serve.pool.resident_tenants",
                    sum(
                        1 for t in self._tenants.values()
                        if t.engine is not None
                    ),
                )

    def refresh_bytes(self, name: str) -> int:
        """Re-account one tenant's resident bytes (after a swap/merge
        changed its version) and re-run the budget sweep."""
        t = self._get(name)
        with self._lock:
            if t.engine is not None:
                t.device_bytes = t.engine.version.device_bytes()
                self._evict_to_budget(protect=t)
            self._gauge_residency()
            return t.device_bytes

    # -- front ends --------------------------------------------------------

    def serve(self, quantum: int | None = None) -> "PoolServer":
        return PoolServer(self, quantum=quantum)

    def stats(self) -> dict:
        with self._lock:
            tenants = {
                name: {
                    "resident": t.engine is not None,
                    "device_bytes": t.device_bytes,
                    "admits": t.admits,
                    "evictions": t.evictions,
                    "weight": t.weight,
                    "queue_depth": (
                        t.server.scheduler.depth()
                        if t.server is not None else 0
                    ),
                    "rejected": (
                        t.server.scheduler.rejected
                        if t.server is not None else 0
                    ),
                }
                for name, t in self._tenants.items()
            }
            return {
                "tenants": tenants,
                "resident_bytes": self._resident_bytes_locked(),
                "byte_budget": self.byte_budget,
                "resident_tenants": sum(
                    1 for t in self._tenants.values()
                    if t.engine is not None
                ),
                "over_budget": self.over_budget,
            }


class PoolServer:
    """One worker thread serving every pool tenant under weighted
    fair queueing (see module docstring). The multi-tenant analog of
    ``api.Server``: ``submit``/``submit_update`` route by tenant name,
    ``pump()`` is the deterministic worker body, ``stats()``/
    ``health()`` aggregate per tenant."""

    def __init__(self, pool: EnginePool, quantum: int | None = None):
        self.pool = pool
        self.wfq = DeficitRoundRobin(quantum)
        self._wake = threading.Condition()
        self._stop = False
        self._worker: threading.Thread | None = None
        self._closed = False
        self.worker_errors = 0
        self.last_worker_error: Exception | None = None
        self._scrape = None  # obs.export.ScrapeServer (serve_metrics)

    def serve_metrics(self, port: int = 0, host: str = "127.0.0.1"
                      ) -> int:
        """Attach the pool's live scrape surface (/metrics, /healthz,
        /statz — see ``Server.serve_metrics``); stopped by close()."""
        from ..obs import export

        return export.attach_scrape(self, port=port, host=host)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "PoolServer":
        if self._closed:
            raise RuntimeError(
                "serve.PoolServer is closed; build a new one via "
                "pool.serve()"
            )
        if self._worker is None or not self._worker.is_alive():
            self._stop = False
            self._worker = threading.Thread(
                target=self._loop, name="combblas-serve-pool",
                daemon=True,
            )
            self._worker.start()
        return self

    def close(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Refuse all tenants' admissions, stop the worker, then drain
        (reads AND pending write merges, in the caller's thread) or
        fail whatever is left."""
        self._closed = True
        for name in self.pool.tenant_names():
            t = self.pool._peek(name)
            if t is not None and t.server is not None:
                t.server.scheduler.close()
        with self._wake:
            self._stop = True
            self._wake.notify_all()
        if self._worker is not None:
            self._worker.join(timeout)
            if self._worker.is_alive():
                raise TimeoutError(
                    f"pool worker did not stop within {timeout}s"
                )
            self._worker = None
        if drain:
            while self.pump(force=True):
                pass
        # per-tenant shutdown: queues are empty after the drain; a
        # no-drain close fails pending reads and aborts buffered writes
        # through each tenant server's own close path
        for name in self.pool.tenant_names():
            t = self.pool._peek(name)
            if t is not None and t.server is not None:
                t.server.close(drain=False, timeout=timeout)
        if self._scrape is not None:
            from ..obs import export

            export.detach_scrape(self)

    def __enter__(self) -> "PoolServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # -- front door --------------------------------------------------------

    def submit(self, tenant: str, kind: str, root,
               timeout_s: float | None = None, trace=None):
        """Admit one query for ``tenant`` — the tenant's own bounded
        queue, SLO budget, breaker and fault injector decide
        (rejections name the tenant); no device work happens here.
        ``trace`` adopts the net frontend's live trace object (round
        19 — see scheduler.submit)."""
        t = self.pool._get(tenant)
        srv = self.pool.server(tenant)
        fut = srv.submit(kind, root, timeout_s=timeout_s, trace=trace)
        t.last_used = time.monotonic()
        with self._wake:
            self._wake.notify_all()
        return fut

    def submit_many(self, tenant: str, kind: str, roots,
                    timeout_s: float | None = None) -> list:
        srv = self.pool.server(tenant)
        out = srv.submit_many(kind, roots, timeout_s=timeout_s)
        with self._wake:
            self._wake.notify_all()
        return out

    def submit_update(self, tenant: str, ops):
        """Admit edge mutations for ``tenant``'s graph. The merge runs
        on the POOL worker under the same WFQ meter as reads (write-
        lane fairness); admission needs the engine resident for the
        version check, so an evicted tenant re-admits here —
        claim/release, so a concurrent budget sweep cannot evict it
        between the admit and the version check."""
        self.pool.claim(tenant)
        try:
            srv = self.pool.server(tenant)
            fut = srv.submit_update(ops)
        finally:
            self.pool.release(tenant)
        with self._wake:
            self._wake.notify_all()
        return fut

    def faults(self, tenant: str):
        """The tenant's own ``FaultInjector`` — per-tenant by
        construction, so one tenant's chaos rules never fire in
        another's execution path."""
        return self.pool.server(tenant).faults

    def warmup(self, tenant: str | None = None, **kw) -> dict:
        """Warm one tenant's plans (or every registered tenant's).
        Admits as needed — warming IS a residency claim."""
        names = (
            [tenant] if tenant is not None
            else self.pool.tenant_names()
        )
        out = {}
        for name in names:
            self.pool.admit(name)
            out[name] = self.pool.server(name).warmup(**kw)
        return out

    # -- the WFQ pump ------------------------------------------------------

    def _updates_due(self, srv, now: float, force: bool) -> bool:
        if force:
            b = srv._upd_buffer
            return b is not None and b.depth() > 0
        return srv._updates_due(now)

    def pump(self, force: bool = False) -> int:
        """One deficit-round-robin scheduling round across every
        backlogged tenant (the worker's body; callable directly for
        deterministic tests). Writes flush FIRST when due (they carry
        their own deadline), then reads while the tenant's balance
        lasts — both charge the same per-tenant meter. Returns
        read-batches + write-merges executed."""
        pool = self.pool
        now = time.monotonic()
        names = pool.tenant_names()
        # tenant churn must not leak WFQ state — nor the dead names'
        # obs label space (prune() returns what it dropped)
        for gone in self.wfq.prune(names):
            obs.prune_labels(tenant=gone)
        backlogged = []
        for name in names:
            t = pool._peek(name)
            if t is None:
                continue  # removed since the snapshot
            self.wfq.add(name, t.weight)  # keeps weight current
            srv = t.server
            if srv is None:
                continue
            if (
                srv.scheduler.has_ready(now)
                or (force and srv.scheduler.depth() > 0)
                or self._updates_due(srv, now, force)
            ):
                backlogged.append(name)
        if not backlogged:
            return 0
        executed = 0
        for name in self.wfq.round(backlogged):
            t = pool._peek(name)
            if t is None or t.server is None:
                continue  # removed mid-round
            srv = t.server
            # write lane first when due: merges have their own
            # deadline (update_max_delay_s) and spend the tenant's
            # share like any read batch would.  claim() admits + marks
            # busy ATOMICALLY — a plain admit-then-busy leaves a
            # window where another thread's budget sweep sees an idle
            # tenant and evicts the engine mid-batch
            if self._updates_due(srv, now, force):
                pool.claim(name)
                try:
                    ops = srv.pump_updates(force=True)
                finally:
                    pool.release(name)
                if ops:
                    self.wfq.charge(name, ops)
                    pool.refresh_bytes(name)
                    executed += 1
            while self.wfq.balance(name) > 0:
                batches = srv.scheduler.pop_ready(
                    force=force, max_batches=1
                )
                if not batches:
                    break
                pool.claim(name)
                try:
                    for reqs in batches:
                        srv._run_batch(reqs)
                        self.wfq.charge(name, len(reqs))
                        executed += 1
                finally:
                    pool.release(name)
        return executed

    # -- worker ------------------------------------------------------------

    def _next_deadline(self) -> float | None:
        deadlines = []
        for name in self.pool.tenant_names():
            t = self.pool._peek(name)
            srv = t.server if t is not None else None
            if srv is None:
                continue
            d = srv.scheduler.next_deadline()
            if d is not None:
                deadlines.append(d)
            b = srv._upd_buffer
            if b is not None:
                age = b.oldest_age()
                if age is not None:
                    deadlines.append(
                        time.monotonic()
                        + max(srv.config.update_max_delay_s - age, 0.0)
                    )
        return min(deadlines) if deadlines else None

    def _has_ready(self) -> bool:
        now = time.monotonic()
        for name in self.pool.tenant_names():
            t = self.pool._peek(name)
            srv = t.server if t is not None else None
            if srv is None:
                continue
            if srv.scheduler.has_ready(now) or srv._updates_due(now):
                return True
        return False

    def _loop(self) -> None:
        while True:
            with self._wake:
                if self._stop:
                    break
            try:
                pumped = self.pump()
                if pumped:
                    continue
            except Exception as e:  # scheduler-bug backstop, like the
                # single-tenant worker: the pool must outlive any one
                # pump — settle nothing here (the recovery ladder
                # already settled batch failures), back off briefly
                self.worker_errors += 1
                self.last_worker_error = e
                obs.count(
                    "serve.worker.errors", exc_type=type(e).__name__,
                    pool=1,
                )
                traceback.print_exc(file=sys.stderr)
                time.sleep(0.05)
                continue
            with self._wake:
                if self._stop:
                    break
                if self._has_ready():
                    continue
                deadline = self._next_deadline()
                if deadline is None:
                    self._wake.wait(_IDLE_WAIT_S)
                else:
                    delay = deadline - time.monotonic()
                    if delay > 0:
                        self._wake.wait(min(delay, _IDLE_WAIT_S))

    # -- introspection -----------------------------------------------------

    def stats(self) -> dict:
        """Pool + per-tenant serving stats: residency/bytes from the
        pool, queue/breaker/disposition from each tenant's server
        (labeled by tenant), WFQ shares from the arbiter."""
        out = self.pool.stats()
        out["wfq"] = self.wfq.describe()
        per_tenant = {}
        for name in self.pool.tenant_names():
            t = self.pool._peek(name)
            if t is None or t.server is None:
                continue
            if t.engine is not None:
                per_tenant[name] = t.server.stats()
            else:  # evicted: engine-side stats unavailable, the
                # scheduler side still reports
                sch = t.server.scheduler
                per_tenant[name] = {
                    "tenant": name,
                    "resident": False,
                    "queue_depth": sch.depth(),
                    "submitted": sch.submitted,
                    "rejected": sch.rejected,
                }
        out["servers"] = per_tenant
        out["worker_errors"] = self.worker_errors
        return out

    def health(self) -> dict:
        """Pool liveness: ``ok`` / ``degraded`` (some tenant's breaker
        not closed) / ``down`` (started worker died) / ``closed``,
        with each tenant's breaker states labeled by tenant."""
        now = time.monotonic()
        breakers = {}
        slo_burn = {}
        degraded = False
        for name in self.pool.tenant_names():
            t = self.pool._peek(name)
            srv = t.server if t is not None else None
            if srv is None:
                continue
            b = {
                k: br.describe(now)
                for k, br in srv.scheduler.breakers.items()
            }
            breakers[name] = b
            if any(x["state"] != "closed" for x in b.values()):
                degraded = True
            if srv.slo is not None:
                d = srv.slo.describe(now)
                slo_burn[name] = d["burn"]
                if d["breached"]:
                    degraded = True
        if self._closed:
            status = "closed"
        elif self._worker is not None and not self._worker.is_alive():
            status = "down"
        elif degraded:
            status = "degraded"
        else:
            status = "ok"
        return {
            "status": status,
            "worker_alive": (
                self._worker is not None and self._worker.is_alive()
            ),
            "closed": self._closed,
            "breakers": breakers,
            # per-tenant SLO budget burn (round 15) — the one number a
            # pool dashboard pages on, worst tenant first
            "slo_burn": slo_burn,
            "slo_burn_worst": max(slo_burn.values()) if slo_burn else None,
            "resident_bytes": self.pool.resident_bytes(),
            "byte_budget": self.pool.byte_budget,
            "worker_errors": self.worker_errors,
        }
