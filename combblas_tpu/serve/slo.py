"""Per-tenant SLO error budgets: rolling-window good/bad accounting
(round 15).

A serving SLO ("99.9% of requests answer inside ``slo_deadline_s``")
is operated through its ERROR BUDGET: over a rolling window, the
tenant may blow the deadline on at most ``(1 - target)`` of its
requests; ``burn = bad / budget`` is the one number a dashboard pages
on (burn >= 1: the budget is exhausted, the SLO is breached for this
window).  This module is the accounting: second-granularity buckets in
a bounded deque, O(1) per record, window sums maintained
incrementally — cheap enough to run on every request disposition.

GOOD = a request settled ok within its deadline.  BAD = timeout
(queue-sweep, pre-execution drop, or during-execution), execution
error / poisoned, or an admission rejection (backpressure, breaker,
SLO queue budget) — a rejected request is a user-visible failure under
an SLO even though it never touched the device.

Wired by ``api.Server`` when ``ServeConfig.slo_deadline_s`` is set
(per tenant by construction in the pool — each tenant's Server owns
its own budget), surfaced through ``stats()``/``health()`` on
``Server``, ``PoolServer`` and ``FleetRouter``, and exported as
``serve.slo.good`` / ``serve.slo.bad`` counters plus the
``serve.slo.budget_burn`` gauge.  A burn crossing 1.0 triggers a
flight-recorder dump (``reason="slo_breach"``).
"""

from __future__ import annotations

import threading
import time
from collections import deque

from .. import obs


class ErrorBudget:
    """Rolling-window good/bad accounting against one SLO target."""

    def __init__(self, target: float = 0.999, window_s: float = 60.0,
                 tenant: str | None = None, clock=time.monotonic):
        if not (0.0 < target < 1.0):
            raise ValueError(
                f"SLO target must be in (0, 1), got {target}"
            )
        if window_s <= 0:
            raise ValueError("SLO window_s must be > 0")
        self.target = float(target)
        self.window_s = float(window_s)
        self.tenant = tenant
        self._clock = clock
        self._lock = threading.Lock()
        # (second-bucket, good, bad), oldest first; window sums kept
        # incrementally so record() never rescans the deque
        self._buckets: deque[list] = deque()
        self._wgood = 0
        self._wbad = 0
        self.good_total = 0
        self.bad_total = 0
        self._breached = False

    def _lab(self, **labels) -> dict:
        if self.tenant is not None:
            labels["tenant"] = self.tenant
        return labels

    def _expire(self, now: float) -> None:
        # caller holds the lock
        horizon = now - self.window_s
        while self._buckets and self._buckets[0][0] < horizon:
            _b, g, bd = self._buckets.popleft()
            self._wgood -= g
            self._wbad -= bd

    def record(self, ok: bool, kind: str = "",
               now: float | None = None) -> bool:
        """Account one request disposition.  Returns True exactly when
        this record BURNS THROUGH the budget (burn crosses >= 1.0) —
        the flight-recorder trigger; repeated bad records while
        already breached return False (one dump per breach episode)."""
        now = self._clock() if now is None else now
        b = int(now)
        with self._lock:
            self._expire(now)
            if self._buckets and self._buckets[-1][0] == b:
                bucket = self._buckets[-1]
            else:
                bucket = [b, 0, 0]
                self._buckets.append(bucket)
            if ok:
                bucket[1] += 1
                self._wgood += 1
                self.good_total += 1
            else:
                bucket[2] += 1
                self._wbad += 1
                self.bad_total += 1
            burn = self._burn_locked()
            breached_now = burn >= 1.0
            transition = breached_now and not self._breached
            self._breached = breached_now
        if obs.ENABLED:
            obs.count(
                "serve.slo.good" if ok else "serve.slo.bad",
                **self._lab(kind=kind),
            )
            obs.gauge("serve.slo.budget_burn", burn, **self._lab())
        return transition

    def _burn_locked(self) -> float:
        total = self._wgood + self._wbad
        if total == 0:
            return 0.0
        # budget > 0 always holds: total > 0 and 0 < target < 1 — a
        # small window just yields a very large burn
        return self._wbad / ((1.0 - self.target) * total)

    def _refresh_locked(self) -> float:
        """Recompute burn after an expiry pass and let a
        breached-then-idle budget RECOVER: once the bad buckets age
        out of the window, ``breached`` must clear even though no new
        record() arrived — otherwise an idle tenant pages as degraded
        forever (and a later breach would not re-fire the recorder)."""
        burn = self._burn_locked()
        if burn < 1.0:
            self._breached = False
        return burn

    def _regauge(self, burn: float) -> None:
        """Re-export the burn gauge on READ-side recomputes too: an
        idle tenant whose bad buckets expired must stop scraping as
        breached — the gauge written at the last record() would
        otherwise page forever."""
        if obs.ENABLED:
            obs.gauge("serve.slo.budget_burn", burn, **self._lab())

    def burn(self, now: float | None = None) -> float:
        now = self._clock() if now is None else now
        with self._lock:
            self._expire(now)
            b = self._refresh_locked()
        self._regauge(b)
        return b

    def describe(self, now: float | None = None) -> dict:
        now = self._clock() if now is None else now
        with self._lock:
            self._expire(now)
            burn = self._refresh_locked()
            out = {
                "target": self.target,
                "window_s": self.window_s,
                "window_good": self._wgood,
                "window_bad": self._wbad,
                "good_total": self.good_total,
                "bad_total": self.bad_total,
                "burn": round(burn, 4),
                "breached": self._breached,
            }
        self._regauge(burn)
        return out
