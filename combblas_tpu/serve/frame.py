"""Transport-agnostic length-prefixed JSON+binary frame codec (r19).

Factored out of ``serve/ipc.py`` (round 17) so ONE implementation
serves both transports: the process fleet's parent<->child
``socketpair`` channels (``serve/procfleet.py``) and the network front
door's TCP connections (``serve/net/``).  Every frame is::

    [4B total_len] [4B header_len] [header JSON] [binary blobs]

Framing means a reader can never consume half a message; a peer that
dies mid-frame produces a clean ``ChannelClosed`` on the next read,
never a poisoned stream — the on-disk analog is the WAL's
torn-final-line tolerance.

The header is one UTF-8 JSON object (debuggable, pickle-free — a
peer crash can corrupt its own heap, not ours).  Numpy arrays do NOT
ride as JSON lists: :func:`encode` hoists them into the frame's binary
section as raw contiguous bytes and leaves an
``{"__ndb__": dtype, "shape": [...], "off": n, "nbytes": n}``
envelope in the header; :func:`decode` rebuilds them with
``np.frombuffer`` — a memcpy, not a float-parse.  That keeps a
pagerank reply (one n-vector per query) at wire cost ~= its array
bytes, which is what lets the serving read path stay exec-bound
instead of serialization-bound.

Big payloads (graph versions) still NEVER ride a channel: they travel
as ``save_version`` checkpoint files on disk and the message carries
the path (``swap_from_checkpoint``), so the wire layer stays
latency-bound, not bandwidth-bound.

The obs accounting series keep their round-18 ``serve.ipc.*`` names
(``serve.ipc.bytes_out/bytes_in/encode_s/decode_s``, labeled by
``peer``) for BOTH transports — one codec, one set of dashboards;
net-specific totals (``serve.net.bytes_in/bytes_out``) are derived by
the frontend from the per-channel byte counters below.
"""

from __future__ import annotations

import json
import socket
import struct
import threading
import time

import numpy as np

from .. import obs

#: Hard cap on one frame — a corrupt length prefix must not allocate
#: gigabytes; real messages are query results (KBs).
MAX_FRAME = 64 << 20


class ChannelClosed(ConnectionError):
    """The peer closed (or broke) the socket — for a replica channel
    this is crash detection, handled by quarantine + respawn; for a
    net connection it is client disconnect, handled by connection
    cleanup (in-flight replies are dropped, never stranded)."""


def _headerable(obj, blobs: list):
    """JSON-safe header view of ``obj``: ndarrays hoist their bytes
    into ``blobs`` and leave an ``__ndb__`` envelope; numpy scalars
    become Python scalars."""
    if isinstance(obj, np.ndarray):
        a = np.ascontiguousarray(obj)
        off = sum(len(b) for b in blobs)
        blobs.append(a.tobytes())
        return {
            "__ndb__": a.dtype.str,
            "shape": list(a.shape),
            "off": off,
            "nbytes": a.nbytes,
        }
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if isinstance(obj, (np.bool_,)):
        return bool(obj)
    if isinstance(obj, dict):
        return {str(k): _headerable(v, blobs) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_headerable(v, blobs) for v in obj]
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    # device arrays and anything else array-like: one host transfer
    try:
        return _headerable(np.asarray(obj), blobs)
    except Exception:
        return repr(obj)


def encode(obj) -> bytes:
    """One frame body: ``[4B header_len][header][blobs]``."""
    blobs: list = []
    head = json.dumps(
        _headerable(obj, blobs), separators=(",", ":")
    ).encode("utf-8")
    return b"".join([struct.pack(">I", len(head)), head, *blobs])


def decode(data: bytes) -> dict:
    (hl,) = struct.unpack(">I", data[:4])
    head = json.loads(data[4:4 + hl].decode("utf-8"))
    binary = memoryview(data)[4 + hl:]
    return _denumpy(head, binary)


def _denumpy(obj, binary):
    if isinstance(obj, dict):
        if "__ndb__" in obj:
            off, nb = int(obj["off"]), int(obj["nbytes"])
            return np.frombuffer(
                binary[off:off + nb], dtype=np.dtype(obj["__ndb__"])
            ).reshape(obj["shape"]).copy()  # own the memory: the
            # frame buffer is released after decode
        return {k: _denumpy(v, binary) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_denumpy(v, binary) for v in obj]
    return obj


def denumpy(obj):
    """Identity helper kept for callers that post-process decoded
    replies (decode() already rebuilt the arrays)."""
    return obj


class Channel:
    """One framed JSON duplex channel over a connected socket.

    ``send`` is thread-safe (the reply path and the heartbeat thread
    share the child's channel; the router's request path and its
    supervisor share the parent's; the frontend's reply callbacks
    share a connection's) and returns the wire length of the frame it
    wrote.  ``recv`` is single-reader by design — each side owns
    exactly one reader thread/loop.

    ``peer`` labels the round-18 channel accounting series
    (``serve.ipc.bytes_out/bytes_in/encode_s/decode_s``) so the
    isolation tax is attributable per peer class; obs disabled costs
    one attribute read per frame.  ``bytes_out``/``bytes_in`` integer
    totals are maintained unconditionally (plain int adds) so
    transports can derive their own byte series without a second
    count at this layer.
    """

    def __init__(self, sock: socket.socket, peer: str | None = None):
        self._sock = sock
        self._lab = {"peer": peer} if peer else {}
        self._wlock = threading.Lock()
        self._closed = False
        # wire totals including the 4B length prefix; bytes_in only
        # advances on whole decoded frames (the single reader may hold
        # a partial frame in _rbuf — not yet a message, not counted)
        self.bytes_out = 0
        self.bytes_in = 0
        # partial-frame accumulator: a recv() that times out MID-FRAME
        # keeps what it read here, so the next call resumes the same
        # frame instead of desyncing (a slow peer mid-sendall — GIL
        # stall, compile, SIGSTOP+SIGCONT — is a late frame, not a
        # broken stream)
        self._rbuf = b""

    def send(self, obj: dict) -> int:
        if obs.ENABLED:
            t0 = time.perf_counter()
            data = encode(obj)
            obs.observe(
                "serve.ipc.encode_s", time.perf_counter() - t0, **self._lab
            )
            obs.count("serve.ipc.bytes_out", len(data) + 4, **self._lab)
        else:
            data = encode(obj)
        if len(data) > MAX_FRAME:
            raise ValueError(
                f"ipc frame too large ({len(data)} bytes); ship big "
                "payloads as checkpoint files, not messages"
            )
        frame = struct.pack(">I", len(data)) + data
        with self._wlock:
            if self._closed:
                raise ChannelClosed("channel closed")
            try:
                self._sock.sendall(frame)
            except (OSError, ValueError) as e:
                raise ChannelClosed(f"peer gone: {e}") from e
            self.bytes_out += len(frame)
        return len(frame)

    def recv(self, timeout: float | None = None) -> dict:
        """One message; ``socket.timeout`` when a whole frame has not
        arrived within ``timeout`` (the reader loop's poll tick —
        partial bytes are RETAINED, so a timeout can never desync the
        framing), ``ChannelClosed`` on EOF/reset/corrupt prefix."""
        self._sock.settimeout(timeout)
        while True:
            if len(self._rbuf) >= 4:
                (n,) = struct.unpack(">I", self._rbuf[:4])
                if n > MAX_FRAME:
                    raise ChannelClosed(f"oversized frame ({n} bytes)")
                if len(self._rbuf) >= 4 + n:
                    data = self._rbuf[4:4 + n]
                    self._rbuf = self._rbuf[4 + n:]
                    self.bytes_in += n + 4
                    if obs.ENABLED:
                        t0 = time.perf_counter()
                        msg = decode(data)
                        obs.observe(
                            "serve.ipc.decode_s",
                            time.perf_counter() - t0,
                            **self._lab,
                        )
                        obs.count(
                            "serve.ipc.bytes_in", len(data) + 4, **self._lab
                        )
                        return msg
                    return decode(data)
            try:
                c = self._sock.recv(1 << 16)
            except socket.timeout:
                raise  # partial frame stays buffered for the next call
            except (OSError, ValueError) as e:
                raise ChannelClosed(f"peer gone: {e}") from e
            if not c:
                raise ChannelClosed("peer closed the channel")
            self._rbuf += c

    def close(self) -> None:
        with self._wlock:
            self._closed = True
            try:
                self._sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._sock.close()
            except OSError:
                pass
