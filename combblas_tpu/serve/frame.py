"""Transport-agnostic length-prefixed JSON+binary frame codec (r19).

Factored out of ``serve/ipc.py`` (round 17) so ONE implementation
serves both transports: the process fleet's parent<->child
``socketpair`` channels (``serve/procfleet.py``) and the network front
door's TCP connections (``serve/net/``).  Every frame is::

    [4B total_len] [4B header_len] [header JSON] [binary blobs]

Framing means a reader can never consume half a message; a peer that
dies mid-frame produces a clean ``ChannelClosed`` on the next read,
never a poisoned stream — the on-disk analog is the WAL's
torn-final-line tolerance.

The header is one UTF-8 JSON object (debuggable, pickle-free — a
peer crash can corrupt its own heap, not ours).  Numpy arrays do NOT
ride as JSON lists: :func:`encode` hoists them into the frame's binary
section as raw contiguous bytes and leaves an
``{"__ndb__": dtype, "shape": [...], "off": n, "nbytes": n}``
envelope in the header; :func:`decode` rebuilds them with
``np.frombuffer`` — a memcpy, not a float-parse.  That keeps a
pagerank reply (one n-vector per query) at wire cost ~= its array
bytes, which is what lets the serving read path stay exec-bound
instead of serialization-bound.  Round 21 adds one typed envelope on
top: :class:`SparseFrontier` rides as ``__spf__`` (dtype-minimized
frontier triples — the sharded hop protocol's sparse wire encoding)
and :func:`pack_bf16`/:func:`unpack_bf16` give dense payloads an
opt-in half-width float codec with no dtype-string dependency.

Big payloads (graph versions) still NEVER ride a channel: they travel
as ``save_version`` checkpoint files on disk and the message carries
the path (``swap_from_checkpoint``), so the wire layer stays
latency-bound, not bandwidth-bound.

The obs accounting series keep their round-18 ``serve.ipc.*`` names
(``serve.ipc.bytes_out/bytes_in/encode_s/decode_s``, labeled by
``peer``) for BOTH transports — one codec, one set of dashboards;
net-specific totals (``serve.net.bytes_in/bytes_out``) are derived by
the frontend from the per-channel byte counters below.
"""

from __future__ import annotations

import json
import select
import socket
import struct
import threading
import time

import numpy as np

from .. import obs

#: Hard cap on one frame — a corrupt length prefix must not allocate
#: gigabytes; real messages are query results (KBs).
MAX_FRAME = 64 << 20
# sender-side no-progress deadline (see Channel._send_frame): a peer
# that drains NOTHING for this long is wedged, not slow.  Generous on
# purpose — boot-sized frames to a child that is still importing its
# JAX runtime on a loaded single-core box legitimately stall for tens
# of seconds; liveness policing belongs to heartbeats, not the wire.
SEND_TIMEOUT_S = 300.0


class ChannelClosed(ConnectionError):
    """The peer closed (or broke) the socket — for a replica channel
    this is crash detection, handled by quarantine + respawn; for a
    net connection it is client disconnect, handled by connection
    cleanup (in-flight replies are dropped, never stranded)."""


class SparseFrontier:
    """Typed sparse-frontier wire payload (round 21): the live COO
    triples of a logically-dense ``[n, width]`` hop operand.

    The sharded hop protocol (``serve/shard.py``) ships O(frontier)
    triples instead of the O(n*W) dense state — the CombBLAS SpMSpV
    stance applied at the wire.  Encoded as a first-class ``__spf__``
    header envelope so both sides get the TYPE back, not a bag of
    arrays; dtypes are wire-minimized: rows ``int32``, lanes ``uint8``
    (batch widths are <= 256 by serve-config construction), values
    ``float32`` or absent entirely (a bfs frontier's values ARE its
    row ids).
    """

    __slots__ = ("n", "width", "rows", "lanes", "vals")

    def __init__(self, n: int, width: int, rows, lanes, vals=None):
        self.n = int(n)
        self.width = int(width)
        if not (1 <= self.width <= 256):
            raise ValueError(
                f"SparseFrontier width must be in [1, 256] (lanes "
                f"ride uint8); got {self.width}"
            )
        self.rows = np.ascontiguousarray(rows, np.int32)
        self.lanes = np.ascontiguousarray(lanes, np.uint8)
        self.vals = (None if vals is None
                     else np.ascontiguousarray(vals, np.float32))

    @property
    def nnz(self) -> int:
        return int(self.rows.shape[0])

    def nbytes(self) -> int:
        """Logical wire bytes of the triple arrays (the router's
        hop-payload accounting surface)."""
        t = self.rows.nbytes + self.lanes.nbytes
        return t + (0 if self.vals is None else self.vals.nbytes)

    def to_dense(self, fill, dtype=None) -> np.ndarray:
        """Host-side scatter into the dense ``[n, width]`` array the
        triples describe: ``fill`` everywhere, ``vals`` (or the row
        ids when vals is None) at the triples."""
        dt = np.dtype(dtype) if dtype is not None \
            else np.asarray(fill).dtype
        out = np.full((self.n, self.width), fill, dt)
        out[self.rows, self.lanes.astype(np.int64)] = (
            self.rows if self.vals is None else self.vals
        )
        return out


def pack_bf16(a: np.ndarray) -> np.ndarray:
    """float32 -> bf16-on-the-wire as raw uint16 (round-to-nearest-
    even via the carry-in bias trick), dependency-free — no ml_dtypes
    on the wire, so both peers agree on the codec by construction."""
    u = np.ascontiguousarray(a, np.float32).view(np.uint32)
    u = u + np.uint32(0x7FFF) + ((u >> np.uint32(16)) & np.uint32(1))
    return (u >> np.uint32(16)).astype(np.uint16)


def unpack_bf16(u: np.ndarray) -> np.ndarray:
    """The decode half of :func:`pack_bf16`: uint16 -> float32 by
    reinstating the truncated mantissa bits as zeros."""
    w = np.ascontiguousarray(u, np.uint16).astype(np.uint32)
    return (w << np.uint32(16)).view(np.float32)


def _headerable(obj, blobs: list):
    """JSON-safe header view of ``obj``: ndarrays hoist their bytes
    into ``blobs`` and leave an ``__ndb__`` envelope; numpy scalars
    become Python scalars."""
    if isinstance(obj, np.ndarray):
        a = np.ascontiguousarray(obj)
        off = sum(len(b) for b in blobs)
        blobs.append(a.tobytes())
        return {
            "__ndb__": a.dtype.str,
            "shape": list(a.shape),
            "off": off,
            "nbytes": a.nbytes,
        }
    if isinstance(obj, SparseFrontier):
        return {"__spf__": {
            "n": obj.n, "width": obj.width,
            "rows": _headerable(obj.rows, blobs),
            "lanes": _headerable(obj.lanes, blobs),
            "vals": (None if obj.vals is None
                     else _headerable(obj.vals, blobs)),
        }}
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if isinstance(obj, (np.bool_,)):
        return bool(obj)
    if isinstance(obj, dict):
        return {str(k): _headerable(v, blobs) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_headerable(v, blobs) for v in obj]
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    # device arrays and anything else array-like: one host transfer
    try:
        return _headerable(np.asarray(obj), blobs)
    except Exception:
        return repr(obj)


def encode(obj) -> bytes:
    """One frame body: ``[4B header_len][header][blobs]``."""
    blobs: list = []
    head = json.dumps(
        _headerable(obj, blobs), separators=(",", ":")
    ).encode("utf-8")
    return b"".join([struct.pack(">I", len(head)), head, *blobs])


def decode(data: bytes) -> dict:
    (hl,) = struct.unpack(">I", data[:4])
    head = json.loads(data[4:4 + hl].decode("utf-8"))
    binary = memoryview(data)[4 + hl:]
    return _denumpy(head, binary)


def _denumpy(obj, binary):
    if isinstance(obj, dict):
        if "__ndb__" in obj:
            off, nb = int(obj["off"]), int(obj["nbytes"])
            return np.frombuffer(
                binary[off:off + nb], dtype=np.dtype(obj["__ndb__"])
            ).reshape(obj["shape"]).copy()  # own the memory: the
            # frame buffer is released after decode
        if "__spf__" in obj:
            m = obj["__spf__"]
            vals = m.get("vals")
            return SparseFrontier(
                int(m["n"]), int(m["width"]),
                _denumpy(m["rows"], binary),
                _denumpy(m["lanes"], binary),
                None if vals is None else _denumpy(vals, binary),
            )
        return {k: _denumpy(v, binary) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_denumpy(v, binary) for v in obj]
    return obj


def denumpy(obj):
    """Identity helper kept for callers that post-process decoded
    replies (decode() already rebuilt the arrays)."""
    return obj


class Channel:
    """One framed JSON duplex channel over a connected socket.

    ``send`` is thread-safe (the reply path and the heartbeat thread
    share the child's channel; the router's request path and its
    supervisor share the parent's; the frontend's reply callbacks
    share a connection's) and returns the wire length of the frame it
    wrote.  ``recv`` is single-reader by design — each side owns
    exactly one reader thread/loop.

    ``peer`` labels the round-18 channel accounting series
    (``serve.ipc.bytes_out/bytes_in/encode_s/decode_s``) so the
    isolation tax is attributable per peer class; obs disabled costs
    one attribute read per frame.  ``bytes_out``/``bytes_in`` integer
    totals are maintained unconditionally (plain int adds) so
    transports can derive their own byte series without a second
    count at this layer.
    """

    def __init__(self, sock: socket.socket, peer: str | None = None):
        self._sock = sock
        self._lab = {"peer": peer} if peer else {}
        self._wlock = threading.Lock()
        self._closed = False
        # wire totals including the 4B length prefix; bytes_in only
        # advances on whole decoded frames (the single reader may hold
        # a partial frame in _rbuf — not yet a message, not counted)
        self.bytes_out = 0
        self.bytes_in = 0
        # partial-frame accumulator: a recv() that times out MID-FRAME
        # keeps what it read here, so the next call resumes the same
        # frame instead of desyncing (a slow peer mid-sendall — GIL
        # stall, compile, SIGSTOP+SIGCONT — is a late frame, not a
        # broken stream)
        self._rbuf = b""

    def send(self, obj: dict) -> int:
        if obs.ENABLED:
            t0 = time.perf_counter()
            data = encode(obj)
            obs.observe(
                "serve.ipc.encode_s", time.perf_counter() - t0, **self._lab
            )
            obs.count("serve.ipc.bytes_out", len(data) + 4, **self._lab)
        else:
            data = encode(obj)
        if len(data) > MAX_FRAME:
            raise ValueError(
                f"ipc frame too large ({len(data)} bytes); ship big "
                "payloads as checkpoint files, not messages"
            )
        frame = struct.pack(">I", len(data)) + data
        with self._wlock:
            if self._closed:
                raise ChannelClosed("channel closed")
            try:
                self._send_frame(frame)
            except (OSError, ValueError) as e:
                raise ChannelClosed(f"peer gone: {e}") from e
            self.bytes_out += len(frame)
        return len(frame)

    def _send_frame(self, frame: bytes) -> None:
        # NOT ``sendall``: ``settimeout`` is socket-GLOBAL, so a
        # concurrent reader polling ``recv`` with a short tick would
        # impose that tick on the whole sendall — and any frame larger
        # than the kernel socket buffer headed to a busy peer (a boot
        # payload to a child still importing its runtime, a dense hop
        # slab mid-compile) would spuriously "time out".  Chunked
        # select+send keeps partial progress across ticks and only
        # gives up after SEND_TIMEOUT_S of ZERO forward progress — a
        # genuinely wedged peer, not a slow one.
        view = memoryview(frame)
        stalled_since = time.monotonic()
        while view:
            _, writable, _ = select.select([], [self._sock], [], 1.0)
            n = 0
            if writable:
                try:
                    n = self._sock.send(view)
                except (socket.timeout, BlockingIOError,
                        InterruptedError):
                    n = 0
            if n:
                view = view[n:]
                stalled_since = time.monotonic()
            elif time.monotonic() - stalled_since > SEND_TIMEOUT_S:
                raise OSError(
                    f"send stalled > {SEND_TIMEOUT_S:g}s "
                    f"({len(view)} bytes undrained)"
                )

    def recv(self, timeout: float | None = None) -> dict:
        """One message; ``socket.timeout`` when a whole frame has not
        arrived within ``timeout`` (the reader loop's poll tick —
        partial bytes are RETAINED, so a timeout can never desync the
        framing), ``ChannelClosed`` on EOF/reset/corrupt prefix."""
        self._sock.settimeout(timeout)
        while True:
            if len(self._rbuf) >= 4:
                (n,) = struct.unpack(">I", self._rbuf[:4])
                if n > MAX_FRAME:
                    raise ChannelClosed(f"oversized frame ({n} bytes)")
                if len(self._rbuf) >= 4 + n:
                    data = self._rbuf[4:4 + n]
                    self._rbuf = self._rbuf[4 + n:]
                    self.bytes_in += n + 4
                    if obs.ENABLED:
                        t0 = time.perf_counter()
                        msg = decode(data)
                        obs.observe(
                            "serve.ipc.decode_s",
                            time.perf_counter() - t0,
                            **self._lab,
                        )
                        obs.count(
                            "serve.ipc.bytes_in", len(data) + 4, **self._lab
                        )
                        return msg
                    return decode(data)
            try:
                c = self._sock.recv(1 << 16)
            except socket.timeout:
                raise  # partial frame stays buffered for the next call
            except (OSError, ValueError) as e:
                raise ChannelClosed(f"peer gone: {e}") from e
            if not c:
                raise ChannelClosed("peer closed the channel")
            self._rbuf += c

    def close(self) -> None:
        with self._wlock:
            self._closed = True
            try:
                self._sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._sock.close()
            except OSError:
                pass
