"""Deterministic fault injection: the testable half of resilience.

Every recovery path in the serve stack (poisoned-batch bisection,
circuit breakers, worker backoff, hot-swap rollback) is only as real as
the failures it has been exercised against. This module provides the
failures: a ``FaultInjector`` holding named **failure points** that the
api worker threads through its execution path, armed with deterministic
rules so a chaos test replays bit-for-bit.

Failure points (``FAULT_POINTS``):

* ``scheduler.admit``  — inside ``Server.submit``'s admission call (the
  ``submit_many`` prefix-semantics probe);
* ``batch.assemble``   — before the lane vector is built;
* ``engine.execute``   — before the device launch (the main chaos knob);
* ``batch.scatter``    — after execution, before results reach futures;
* ``engine.swap``      — inside ``Server.swap_graph``, before the
  atomic pointer flip (a failed build/validate must leave the old
  version serving);
* ``update.submit``    — inside ``Server.submit_update``'s admission
  (the write lane's front door);
* ``update.merge``     — in the mutation thread, before
  ``engine.apply_delta`` runs (a failed merge must fail exactly the
  updates it carried and leave the current version serving);
* ``wal.append`` / ``checkpoint.save`` / ``replica.death`` /
  ``fleet.fanout`` — the round-16 durability & self-healing points
  (see the ``FAULT_POINTS`` comment below for each one's contract).

Rules, all deterministic:

* ``script(point, at=(3, 7))``       — fire on exact call indices
  (0-based per point);
* ``rate(point, 0.05, seed=42)``     — seeded Bernoulli per call
  (``numpy.random.default_rng``: same seed + same call order = same
  schedule);
* ``when(point, predicate)``         — fire when ``predicate(ctx)`` is
  true (e.g. "the batch contains root 13" — the poison-request shape).

An unarmed injector (``FaultInjector()`` with no rules) costs one
attribute read per check — servers carry one by default, so production
paths pay nothing. Fired faults raise ``InjectedFault`` (a
``RuntimeError``) and count ``serve.faults.injected{point=...}`` in obs.

Usage::

    srv = engine.serve(cfg)
    srv.faults.rate("engine.execute", 0.05, seed=7)
    srv.faults.script("batch.scatter", at=(2,))
    srv.faults.when("engine.execute",
                    lambda ctx: 13 in ctx.get("roots", ()))
"""

from __future__ import annotations

import threading

from .. import obs

#: Named failure points the serve stack threads through the injector.
#: Round 16 adds the durability / self-healing points:
#: ``wal.append`` (inside ``submit_update``, before the write is
#: acknowledged — a failed append must reject the write, never
#: acknowledge an undurable one), ``checkpoint.save`` (the background
#: checkpointer — a failed snapshot must leave the previous one and
#: the un-truncated WAL intact), ``replica.death`` (checked at the top
#: of the api worker loop OUTSIDE its recovery ladder, so firing it
#: kills the worker thread — the fleet supervisor's detection target),
#: and ``fleet.fanout`` (per-replica inside ``FleetRouter.fan_out`` —
#: a failed replica rebuild must lag visibly, not abort the fleet).
FAULT_POINTS = (
    "scheduler.admit",
    "batch.assemble",
    "engine.execute",
    "batch.scatter",
    "engine.swap",
    "update.submit",
    "update.merge",
    "wal.append",
    "checkpoint.save",
    "replica.death",
    "fleet.fanout",
)


class InjectedFault(RuntimeError):
    """A failure produced by the injection framework (never by real
    code) — recovery paths treat it like any other execution error;
    tests and the chaos bench match on this type to separate injected
    damage from genuine regressions."""

    def __init__(self, point: str, call: int, rule: str):
        super().__init__(
            f"injected fault at {point!r} (call #{call}, rule {rule})"
        )
        self.point = point
        self.call = call
        self.rule = rule


class _Rule:
    """One armed failure rule; ``fires(call, ctx)`` must be
    deterministic given the call index and context."""

    kind = "rule"

    def fires(self, call: int, ctx: dict) -> bool:  # pragma: no cover
        raise NotImplementedError


class _Script(_Rule):
    kind = "script"

    def __init__(self, at):
        self.at = frozenset(int(i) for i in at)

    def fires(self, call, ctx):
        return call in self.at


class _Rate(_Rule):
    kind = "rate"

    def __init__(self, p: float, seed: int):
        import numpy as np

        if not (0.0 <= p <= 1.0):
            raise ValueError(f"fault rate must be in [0, 1], got {p}")
        self.p = float(p)
        self.seed = int(seed)
        self._rng = np.random.default_rng(self.seed)

    def fires(self, call, ctx):
        # one draw per call, in call order: the schedule is a pure
        # function of (seed, call sequence) — replayable
        return bool(self._rng.random() < self.p)


class _When(_Rule):
    kind = "when"

    def __init__(self, predicate):
        self.predicate = predicate

    def fires(self, call, ctx):
        return bool(self.predicate(ctx))


class FaultInjector:
    """Per-server registry of armed failure rules.

    Thread-safe; ``check(point, **ctx)`` is the only call sites ever
    make. With no rules armed it returns after one attribute read.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._rules: dict[str, list[_Rule]] = {}
        self._armed = False  # fast-path guard, see check()
        self.calls: dict[str, int] = {}
        self.fired: dict[str, int] = {}

    # -- arming --------------------------------------------------------------

    def _add(self, point: str, rule: _Rule) -> "FaultInjector":
        if point not in FAULT_POINTS:
            raise ValueError(
                f"unknown fault point {point!r}; known: {FAULT_POINTS}"
            )
        with self._lock:
            self._rules.setdefault(point, []).append(rule)
            self._armed = True
        return self

    def script(self, point: str, at) -> "FaultInjector":
        """Fire on exact 0-based call indices of ``point``."""
        return self._add(point, _Script(at))

    def rate(self, point: str, p: float, seed: int = 0) -> "FaultInjector":
        """Fire each call with probability ``p``, drawn from a seeded
        generator — deterministic given the call order."""
        return self._add(point, _Rate(p, seed))

    def when(self, point: str, predicate) -> "FaultInjector":
        """Fire whenever ``predicate(ctx)`` is true (the poisoned-
        request shape: e.g. ``lambda ctx: 13 in ctx["roots"]``)."""
        return self._add(point, _When(predicate))

    def clear(self, point: str | None = None) -> None:
        """Disarm one point (or all); counters are retained."""
        with self._lock:
            if point is None:
                self._rules.clear()
            else:
                self._rules.pop(point, None)
            self._armed = bool(self._rules)

    # -- the failure points call this ---------------------------------------

    def check(self, point: str, **ctx) -> None:
        """Raise ``InjectedFault`` when an armed rule fires for this
        call of ``point``; otherwise a near-no-op. Call indices advance
        only while the point is armed, so a script's indices refer to
        calls under injection, not the server's whole lifetime."""
        if not self._armed:
            return
        with self._lock:
            rules = self._rules.get(point)
            if not rules:
                return
            call = self.calls.get(point, 0)
            self.calls[point] = call + 1
            hit = None
            for rule in rules:
                if rule.fires(call, ctx):
                    hit = rule
                    break
            if hit is None:
                return
            self.fired[point] = self.fired.get(point, 0) + 1
        obs.count("serve.faults.injected", point=point, rule=hit.kind)
        raise InjectedFault(point, call, hit.kind)

    # -- introspection -------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {
                "armed": sorted(self._rules),
                "calls": dict(self.calls),
                "fired": dict(self.fired),
            }


class ProcessFaultPlan:
    """Deterministic PROCESS-level chaos (round 17, the process
    fleet): scripted real signals against replica subprocesses,
    keyed by the fleet's routed-submit call index so a chaos run
    replays bit-for-bit — the ``FaultInjector`` philosophy lifted to
    OS crash domains.

    Two failure modes, because they fail DIFFERENTLY:

    * ``sigkill(at, replica)`` — instant crash: the process exits,
      the channel breaks, ``Popen.poll()`` reports it; supervision
      sees it within one tick.
    * ``sigstop(at, replica)`` — a HANG, not a death: the process
      stays alive and the socket stays open, but heartbeats stop and
      in-flight RPCs run out their deadlines; only the heartbeat
      timeout can catch it.  ``sigcont(at, replica)`` un-wedges (for
      tests that assert a stalled replica is routed around and then
      recovers — though quarantine's SIGKILL usually collapses it
      first).

    ``replica`` is an index or ``"home"`` (resolved at FIRE time —
    after a promotion, "home" tracks the lineage, which is what a
    kill-the-home chaos scenario means).  ``ProcessFleet.submit``
    calls :meth:`step` once per routed query and applies what is due.

    Unarmed cost: one attribute read per routed submit.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._rules: list[tuple[int, str, object]] = []  # (at, sig, replica)
        self._armed = False
        self.calls = 0
        self.fired: list[tuple[int, str, object]] = []

    def _add(self, at: int, sig: str, replica) -> "ProcessFaultPlan":
        with self._lock:
            self._rules.append((int(at), sig, replica))
            self._armed = True
        return self

    def sigkill(self, at: int, replica="home") -> "ProcessFaultPlan":
        """SIGKILL ``replica`` at routed-submit call index ``at``."""
        return self._add(at, "SIGKILL", replica)

    def sigstop(self, at: int, replica="home") -> "ProcessFaultPlan":
        """SIGSTOP (wedge, do not kill) ``replica`` at call ``at``."""
        return self._add(at, "SIGSTOP", replica)

    def sigcont(self, at: int, replica="home") -> "ProcessFaultPlan":
        return self._add(at, "SIGCONT", replica)

    def step(self) -> list[tuple[str, object]]:
        """Advance one routed-submit call; returns the ``(signal,
        replica)`` actions due at this index, in arming order."""
        if not self._armed:
            return []
        with self._lock:
            call = self.calls
            self.calls += 1
            due = [
                (sig, rep) for at, sig, rep in self._rules
                if at == call
            ]
            for d in due:
                self.fired.append((call, *d))
            return due

    def stats(self) -> dict:
        with self._lock:
            return {
                "rules": list(self._rules),
                "calls": self.calls,
                "fired": list(self.fired),
            }
