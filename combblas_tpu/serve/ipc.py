"""Process-fleet IPC: the frame codec under its historical name (r17).

Round 19 factored the length-prefixed JSON+binary codec into
``serve/frame.py`` so the process fleet and the network front door
(``serve/net/``) share ONE implementation — one codec, two transports,
no copy-paste drift.  This module is the procfleet-facing alias kept
for every existing import site (``from .ipc import Channel,
ChannelClosed``) and for the obs series names, which stay
``serve.ipc.*`` for both transports (frame.py documents why).

See ``serve/frame.py`` for the wire format, the ``__ndb__`` ndarray
hoisting, and the Channel threading contract.
"""

from __future__ import annotations

from .frame import (  # noqa: F401
    MAX_FRAME,
    Channel,
    ChannelClosed,
    _denumpy,
    _headerable,
    decode,
    denumpy,
    encode,
)

__all__ = [
    "MAX_FRAME",
    "Channel",
    "ChannelClosed",
    "decode",
    "denumpy",
    "encode",
]
