"""Cross-host sharded serving: one huge graph, N slices, one service.

Every replica the fleet has built so far holds the WHOLE graph — the
r9 scale-18 OOM wall is therefore also the serving capacity wall.
This module partitions one graph's row space over N independent
processes (the paper's 2D `CommGrid` distribution collapsed to the
1D row slabs the batched [n, W] serve kernels actually consume) and
serves the union as ONE engine:

* ``plan_partition`` / ``shard_coo`` — balanced contiguous row slabs;
  slice i owns global rows ``[row0, row1)`` as a RECTANGULAR
  ``ls x n`` ``EllParMat`` (the existing ``_build_version`` handles
  rectangles), so per-slice resident device bytes scale ~1/p.
* ``SliceRuntime`` — everything that lives INSIDE one slice process:
  the slab ``GraphVersion``, jitted per-hop step programs (the same
  step bodies as ``models/bfs.py`` / ``models/sssp.py``, re-closed
  over the slab operands — literal SPMD: one program, N data), the
  per-slice WAL + slab snapshots, and slab recovery.
* ``LocalSlice`` / ``ProcSlice`` — the parent-side handles: in-process
  (the fast tier-1 representative) and subprocess (its own JAX
  runtime behind the framed IPC channel, ``serve/_shardworker.py``).
* ``ShardedEngine`` — duck-types ``GraphEngine`` for ``serve/api.py``:
  queries fan in through the EXISTING batcher, each hop executes on
  every slice in parallel, the router gathers slab outputs at the
  owning slice and feeds the concatenated frontier back — a
  bulk-synchronous mirror of the single-program ``while_loop`` with
  IDENTICAL iteration semantics (the step always runs at least once;
  continue iff any slice found new work and ``niter`` is under the
  cap), so bfs/sssp answers are BIT-EXACT vs an unsharded engine
  (their per-row combines — SELECT2ND_MAX, min — are
  order-independent, so the slab bucket layout cannot change them).

The hop datapath (round 21) is the CombBLAS SpMSpV stance applied at
the wire: slab-local loop state (bfs ``parents``/``levels``, the sssp
resident global ``d``, propagate's last slab ``q``) stays DEVICE-
RESIDENT on its slice across the hops of one batch, keyed by a
per-batch epoch token the router mints, and only the live frontier
crosses the wire — as dtype-minimized ``SparseFrontier`` triples when
it is sparse, falling back to the dense ``[n, W]`` operand per hop
when it crosses the density threshold (the diropt regime switch,
decided by the ROUTER and stamped in the payload — never a trace-time
branch; ``COMBBLAS_SHARD_FRONTIER`` forces either encoding).  The
sparse frontier scatters into the dense operand ON DEVICE through a
pow2-bucketed static-capacity scatter prologue (every bucket
pre-traced at warmup — zero post-warmup retraces under every
encoding), and the final gather fetches slab state ONCE at batch end
(``collect``) instead of every hop.  Replay stays idempotent: a slice
that dies mid-batch fails the hop future, the router heals it and
replays the whole batch under a FRESH epoch (re-seeding resident
state everywhere); a respawned slice that is asked to advance an
epoch it never saw answers ``StaleEpochError`` — a protocol fact,
not a death — and the router replays without quarantining it.
Propagate's inherently-dense ``q`` can opt into bf16 wire encoding
(``COMBBLAS_SHARD_WIRE=bf16``, quantization error obs-tracked).

Durability is ENGINE-OWNED (``owns_durability``): writes route
through per-slice WALs with a coordinated two-phase protocol —
phase 1 appends the FULL batch (global coordinates, contiguous
sequence numbers) to every slice's log (any failure tombstones the
appended slices and fails the write); phase 2 applies the
row-filtered, slab-translated sub-batch on every live slice
(idempotent: a commit at-or-below a slice's frontier is a no-op, so
post-heal re-commits and recovery replay compose).  The scalar
``GraphVersion.wal_seq`` snapshot stamp becomes a VECTOR frontier:
each slab snapshot carries its own scalar stamp on the SHARED global
sequence line, and the service manifest (``shard_manifest.json``)
records the per-slice vector — recovery brings each slice to its own
frontier independently and the vector re-converges at the next
commit.

Slice recovery reuses procfleet's sticky quarantine/respawn stance at
slice granularity: ``supervise_once`` collapses a dead/hung slice
(SIGKILL — never negotiated with), respawns it from its slab
snapshot + WAL suffix with capped-backoff retry, and the OTHER slices
keep serving throughout (reads heal-and-retry, bounded).  The network
front door runs UNCHANGED on top — the proof this is one service.

Obs series live under ``serve.shard.*`` (cataloged in
``obs/metrics.py``); the acceptance gate is ``BENCH_SERVE_SHARD=1``
(benchmarks/serve_bench.py, r20).
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
from concurrent.futures import Future
from types import SimpleNamespace

import numpy as np

from .. import obs
from ..dynamic import wal as dyn_wal
from ..dynamic.delta import DeltaBatch
from ..tuner import config as tuner_config
from ..utils import checkpoint as ckpt
from .frame import SparseFrontier, pack_bf16, unpack_bf16
from .ipc import Channel
from .policy import ReplicaDeadError, StaleEpochError
from .procfleet import IpcTimeoutError, ReplicaProc

#: Manifest schema tag (refused at recovery when mismatched — the
#: plan-store convention: never guess at an incompatible layout).
MANIFEST_SCHEMA = "combblas_tpu.shard_manifest/v1"
MANIFEST_NAME = "shard_manifest.json"

#: Per-slice feature-table slab file (features are edge-independent,
#: so they are persisted ONCE at build, not per snapshot).
FEATURES_NAME = "features.npy"

#: Kinds the sharded router can execute.  pagerank/bc need whole-graph
#: normalization / backward sweeps that do not decompose into the
#: stateless row-slab hop protocol — they stay on unsharded engines.
SHARDED_KINDS = ("bfs", "sssp", "propagate")

#: Smallest sparse-scatter capacity bucket: frontiers pad UP to a pow2
#: capacity so every bucket is exactly one trace; 64 keeps the bucket
#: count logarithmic without wasting wire on tiny frontiers (padding
#: is ADDED slice-side before the device scatter, never shipped).
SCATTER_CAP_FLOOR = 64


def _pow2_cap(nnz: int, floor: int = SCATTER_CAP_FLOOR) -> int:
    """The pow2 scatter-capacity bucket for ``nnz`` frontier triples."""
    cap = int(floor)
    while cap < nnz:
        cap <<= 1
    return cap


def _pad_triples(sf: SparseFrontier, cap: int, n: int):
    """Pad triple arrays to the pow2 capacity with OUT-OF-RANGE rows
    (``row == n``): the device scatter runs ``mode='drop'``, so pad
    entries vanish without a mask operand — one trace per bucket, any
    nnz inside it."""
    pad = cap - sf.nnz
    rows = np.concatenate([sf.rows, np.full(pad, n, np.int32)])
    lanes = np.concatenate([
        sf.lanes.astype(np.int32), np.zeros(pad, np.int32)
    ])
    vals = None if sf.vals is None else np.concatenate([
        sf.vals, np.zeros(pad, np.float32)
    ])
    return rows, lanes, vals


def _payload_nbytes(obj) -> int:
    """Logical wire bytes of a hop payload/reply: the array payloads
    that dominate the frame (JSON header overhead excluded — it is
    O(100 B) against KB..MB of state)."""
    if isinstance(obj, np.ndarray):
        return obj.nbytes
    if isinstance(obj, SparseFrontier):
        return obj.nbytes()
    if isinstance(obj, dict):
        return sum(_payload_nbytes(v) for v in obj.values())
    if isinstance(obj, (list, tuple)):
        return sum(_payload_nbytes(v) for v in obj)
    return 0


def _pack_q_wire(q: np.ndarray, wire: str | None) -> dict:
    """Encode a dense float payload for the wire: raw f32, or bf16
    halved-width uint16 when the router stamped ``wire=bf16``."""
    if wire == "bf16":
        return {"q": pack_bf16(q), "wire": "bf16"}
    return {"q": np.asarray(q, np.float32), "wire": "f32"}


def _unpack_q(m: dict) -> np.ndarray:
    q = m["q"]
    if m.get("wire") == "bf16":
        return unpack_bf16(q)
    return np.asarray(q, np.float32)


# --------------------------------------------------------------------------
# partition planning
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ShardSpec:
    """A contiguous row-slab partition of ``[0, nrows)``."""

    nrows: int
    ncols: int
    bounds: tuple  # tuple[(row0, row1), ...] — slice i owns [row0, row1)

    @property
    def nslices(self) -> int:
        return len(self.bounds)

    def owner_of(self, row: int) -> int:
        for i, (a, z) in enumerate(self.bounds):
            if a <= row < z:
                return i
        raise ValueError(f"row {row} outside [0, {self.nrows})")


def plan_partition(nrows: int, nslices: int,
                   ncols: int | None = None) -> ShardSpec:
    """Balanced contiguous row slabs: the first ``nrows % nslices``
    slices get one extra row — every slice within one row of ideal,
    and slab membership is one integer compare (no owner table)."""
    n = int(nrows)
    p = int(nslices)
    if not 1 <= p <= n:
        raise ValueError(f"need 1 <= nslices <= nrows, got {p} / {n}")
    base, extra = divmod(n, p)
    bounds = []
    r0 = 0
    for i in range(p):
        r1 = r0 + base + (1 if i < extra else 0)
        bounds.append((r0, r1))
        r0 = r1
    return ShardSpec(nrows=n, ncols=int(ncols if ncols is not None
                                         else n), bounds=tuple(bounds))


def shard_coo(spec: ShardSpec, i: int, rows, cols, weights=None):
    """Slice ``i``'s slab of a global COO: rows TRANSLATED to slab
    coordinates (``- row0``), columns kept global (the slab matrix is
    ``ls x ncols`` — hops read the full frontier)."""
    rows = np.asarray(rows)
    cols = np.asarray(cols)
    r0, r1 = spec.bounds[i]
    m = (rows >= r0) & (rows < r1)
    w = None if weights is None else np.asarray(weights)[m]
    return rows[m] - r0, cols[m], w


# --------------------------------------------------------------------------
# the slice runtime (lives inside the owning process)
# --------------------------------------------------------------------------


@dataclasses.dataclass
class _SlicePlan:
    kind: str
    width: int
    fn: object
    scatter: object = None   # jitted sparse-frontier scatter prologue
    traces: int = 0
    executions: int = 0


class SliceRuntime:
    """One slice's resident state + jitted hop programs + durability.

    Hosted either in-process (``LocalSlice``) or inside a
    ``_shardworker`` subprocess (``ProcSlice``); either way the op
    surface is :func:`dispatch_slice_op` — one protocol, two
    transports, the ``frame.py`` precedent.
    """

    def __init__(self, grid, idx: int, row0: int, row1: int,
                 nrows: int, ncols: int, version, kinds, *,
                 home: str | None = None, fsync: str | None = None,
                 features=None, max_iters: int | None = None,
                 propagate_hops: int = 2,
                 checkpoint_every: int = 0,
                 checkpoint_retain: int = 2):
        self.grid = grid
        self.idx = int(idx)
        self.row0 = int(row0)
        self.row1 = int(row1)
        self.ls = self.row1 - self.row0
        self.nrows = int(nrows)    # GLOBAL row count
        self.ncols = int(ncols)    # global column space
        self.version = version     # slab GraphVersion (nrows == ls)
        self.kinds = tuple(kinds)
        self.max_iters = max_iters
        self.propagate_hops = int(propagate_hops)
        self.home = home
        self.checkpoint_every = int(checkpoint_every)
        self.checkpoint_retain = max(1, int(checkpoint_retain))
        self._commits_since_ckpt = 0
        self.wal = dyn_wal.open_wal(home, fsync=fsync) \
            if home is not None else None
        self._plans: dict = {}
        # per-kind slice-resident loop state, keyed by the router's
        # batch epoch (round 21): parents/levels slabs (bfs), the
        # global d operand (sssp), the last hop's q slab (propagate)
        self._resident: dict = {}
        self._lock = threading.Lock()
        self.plan_hits = 0
        self.plan_misses = 0
        self.swaps = 0
        self.worker_errors = 0
        # the slab feature table (propagate): rows [row0, row1) of the
        # global [n, F] table, pow2-padded, device-resident — kept OFF
        # the slab GraphVersion (its X restore path assumes square) and
        # re-attached from ``features.npy`` at recovery
        self.X = None
        self.feat_dim = 0
        if features is not None:
            self.attach_features(features)
        self._row_gids = None  # lazy [1, ls] global row-id operand

    # -- construction / recovery ------------------------------------------

    @classmethod
    def build(cls, grid, idx: int, row0: int, row1: int, nrows: int,
              ncols: int, rows, cols, weights, kinds, *,
              features=None, headroom=None, home: str | None = None,
              fsync: str | None = None, max_iters=None,
              propagate_hops: int = 2, checkpoint_every: int = 0,
              checkpoint_retain: int = 2,
              bootstrap_checkpoint: bool = True) -> "SliceRuntime":
        """Build one slice from its slab COO (rows ALREADY translated
        to slab coordinates — see ``shard_coo``)."""
        from .engine import _build_version

        ls = int(row1) - int(row0)
        # the slab version needs only the structural/weighted slab
        # matrices: propagate's hop reuses E (symmetric-graph
        # requirement enforced router-side) and its feature slab is
        # attached separately below
        build_kinds = tuple(
            k for k in kinds if k in ("bfs", "sssp")
        ) or ("bfs",)
        version = _build_version(
            grid, np.asarray(rows), np.asarray(cols), ls, int(ncols),
            weights, build_kinds, False, True, features=None,
            headroom=headroom,
        )
        feats_slab = None
        if features is not None:
            feats_slab = np.asarray(
                features, np.float32
            )[int(row0):int(row1)]
        rt = cls(
            grid, idx, row0, row1, nrows, ncols, version, kinds,
            home=home, fsync=fsync, features=feats_slab,
            max_iters=max_iters, propagate_hops=propagate_hops,
            checkpoint_every=checkpoint_every,
            checkpoint_retain=checkpoint_retain,
        )
        if home is not None:
            if feats_slab is not None:
                np.save(os.path.join(home, FEATURES_NAME), feats_slab)
            if bootstrap_checkpoint:
                # durability floor: recovery needs at least one
                # snapshot to anchor the WAL-suffix replay (the
                # Server._attach_durability precedent)
                rt.checkpoint_now(reason="bootstrap")
        return rt

    @classmethod
    def recover(cls, grid, idx: int, home: str, kinds, *,
                fsync: str | None = None, max_iters=None,
                propagate_hops: int = 2, checkpoint_every: int = 0,
                checkpoint_retain: int = 2) -> "SliceRuntime":
        """Slab crash recovery: latest slab snapshot + per-slice WAL
        suffix, each replayed batch row-filtered to the slab and
        translated (``recover_version(batch_filter=...)``) — brings
        THIS slice to its own frontier without touching the rest."""
        wal = dyn_wal.open_wal(home, fsync=fsync)
        try:
            probe = ckpt.load_latest_version(home, grid,
                                             writable=False)[0]
            shard = (getattr(probe, "extra_meta", None) or {}).get(
                "shard"
            )
            if shard is None:
                raise dyn_wal.RecoveryError(
                    f"snapshots in {home!r} carry no shard descriptor"
                    " (not a slice home?)"
                )
            row0, row1 = int(shard["row0"]), int(shard["row1"])
            nrows, ncols = int(shard["nrows"]), int(shard["ncols"])

            def slab_filter(batch):
                m = (batch.rows >= row0) & (batch.rows < row1)
                if not m.any():
                    return None
                return DeltaBatch(
                    rows=batch.rows[m] - row0, cols=batch.cols[m],
                    vals=batch.vals[m], ops=batch.ops[m],
                    first_seq=batch.first_seq,
                    last_seq=batch.last_seq, oldest_at=0.0,
                )

            build_kinds = tuple(
                k for k in kinds if k in ("bfs", "sssp")
            ) or ("bfs",)
            version = dyn_wal.recover_version(
                home, wal, grid, kinds=build_kinds,
                batch_filter=slab_filter,
            )
        except BaseException:
            wal.close()
            raise
        feats = None
        fpath = os.path.join(home, FEATURES_NAME)
        if os.path.exists(fpath):
            feats = np.load(fpath)
        rt = cls(
            grid, idx, row0, row1, nrows, ncols, version, kinds,
            home=None, fsync=fsync, features=feats,
            max_iters=max_iters, propagate_hops=propagate_hops,
            checkpoint_every=checkpoint_every,
            checkpoint_retain=checkpoint_retain,
        )
        rt.home = home
        rt.wal = wal
        obs.count("serve.shard.recoveries", slice=idx)
        return rt

    def attach_features(self, feats_slab) -> None:
        from ..parallel.spmm import pad_features
        from ..parallel.vec import DistMultiVec

        feats_slab = np.asarray(feats_slab, np.float32)
        if feats_slab.shape[0] != self.ls:
            raise ValueError(
                f"feature slab rows {feats_slab.shape[0]} != slab "
                f"height {self.ls}"
            )
        self.feat_dim = int(feats_slab.shape[1])
        self.X = DistMultiVec.from_global(
            self.grid, pad_features(feats_slab), align="row"
        )

    # -- jitted slab step programs ----------------------------------------

    def _slab_row_gids(self):
        """[1, ls] GLOBAL row ids of this slab as a materialized device
        operand (the ``_gid_blocks`` stance: in-program iota serializes
        inside loop fusions; unsharded on a 1-device grid — the 25x
        sharded-operand pathology, probe_seq_r5 w3)."""
        if self._row_gids is None:
            import jax
            import jax.numpy as jnp

            g = (self.row0 + np.arange(self.ls, dtype=np.int32))[None]
            self._row_gids = jax.device_put(jnp.asarray(g))
        return self._row_gids

    def plan(self, kind: str, width: int) -> _SlicePlan:
        if kind not in self.kinds:
            raise ValueError(
                f"slice was not built for kind {kind!r} "
                f"(kinds={self.kinds})"
            )
        key = (kind, int(width))
        with self._lock:
            p = self._plans.get(key)
        if p is not None:
            self.plan_hits += 1
            return p
        self.plan_misses += 1
        p = self._build_plan(kind, int(width))
        with self._lock:
            self._plans[key] = p
        return p

    def _build_plan(self, kind: str, width: int) -> _SlicePlan:
        """One jitted hop program per (kind, width) — the EXACT step
        body of the unsharded while_loop (models/bfs.py /
        models/sssp.py / models/propagate.py), re-closed over the slab
        operands, with the loop state as ARGUMENTS (the router is the
        loop).  Operands resolve at call time from ``self.version`` so
        a merge swap keeps every compiled executable (zero retraces —
        same shapes, same jit signature)."""
        import jax
        import jax.numpy as jnp

        from ..parallel.ellmat import (
            dist_spmv_ell_masked_multi, dist_spmv_ell_multi,
        )
        from ..parallel.spmm import dist_spmm_ell
        from ..parallel.vec import DistMultiVec
        from ..semiring import MIN_PLUS, PLUS_TIMES, SELECT2ND_MAX

        grid = self.grid
        n = self.ncols
        ls = self.ls
        row0, row1 = self.row0, self.row1
        plan = _SlicePlan(kind=kind, width=width, fn=None)

        def trace_mark():
            plan.traces += 1
            obs.count("trace.serve.shard", kind=kind, width=width,
                      slice=self.idx)

        def mkcol(x):
            return DistMultiVec(blocks=x[None], length=n,
                                align="col", grid=grid)

        if kind == "bfs":

            def impl(E, row_gids, x, parents, levels, level):
                # x: [n, W] global frontier (v if newly visited else
                # -1); parents/levels: [ls, W] slab state; level: the
                # router's niter (a device scalar — NOT static, or
                # every hop would retrace)
                trace_mark()
                pb, lb = parents[None], levels[None]
                unvisited = DistMultiVec(
                    blocks=pb < 0, length=ls, align="row", grid=grid
                )
                y = dist_spmv_ell_masked_multi(
                    SELECT2ND_MAX, E, mkcol(x), unvisited
                )
                new = (
                    (y.blocks >= 0) & (pb < 0)
                    & (row_gids[:, :, None] >= 0)
                )
                pb = jnp.where(new, y.blocks, pb)
                lb = jnp.where(new, level + 1, lb)
                x_next = jnp.where(
                    new, row_gids[:, :, None], jnp.int32(-1)
                )
                # no any_new output: the host derives activity from the
                # discovered nnz it extracts for the wire anyway
                return pb[0], lb[0], x_next[0]

            jitted = jax.jit(impl)
            plan.fn = lambda x, p, l, level: jitted(
                self.version.E, self._slab_row_gids(), x, p, l, level
            )

            def scatter_impl(rows, lanes):
                # sparse-frontier prologue: pow2-capacity triple
                # arrays scattered into the dense [n, W] operand the
                # hop body consumes.  Pad rows are OUT OF RANGE
                # (== n) and vanish under mode='drop' — one trace per
                # capacity bucket, any nnz inside it.  Flattened to a
                # rank-1 scatter (pad index lands >= n*width, still
                # dropped): one index dim keeps XLA:CPU on its fast
                # path, ~25% cheaper at saturated-hop capacities.
                trace_mark()
                x = jnp.full((n * width,), jnp.int32(-1))
                idx = rows * width + lanes
                return x.at[idx].set(rows, mode="drop").reshape(
                    n, width
                )

            plan.scatter = jax.jit(scatter_impl)

        elif kind == "sssp":

            def impl(E, d):
                # d: [n, W] global distances; slab rows sliced with
                # STATIC bounds (row0/row1 are trace-time constants)
                trace_mark()
                relaxed = dist_spmv_ell_multi(MIN_PLUS, E, mkcol(d))
                db = d[row0:row1]
                nb = jnp.minimum(db, relaxed.blocks[0])
                # changed MASK (not a reduced flag): the host extracts
                # exactly the relaxed entries for the sparse wire
                return nb, nb < db

            jitted = jax.jit(impl)
            plan.fn = lambda d: jitted(self._sssp_operand(), d)

            def scatter_impl(d, rows, lanes, vals):
                # scatter-MIN of inbound relaxations into the resident
                # global d (min is idempotent + commutative, so a
                # slice's own broadcast entries fold in harmlessly);
                # rank-1 indexing for the same XLA:CPU fast path as
                # the bfs prologue
                trace_mark()
                w = d.shape[1]
                idx = rows * w + lanes
                return d.reshape(-1).at[idx].min(
                    vals, mode="drop"
                ).reshape(d.shape)

            plan.scatter = jax.jit(scatter_impl)

        elif kind == "propagate":
            if self.X is None:
                raise ValueError(
                    "slice was built without a feature slab "
                    "(features= opts into 'propagate')"
                )

            def hop(E, q):
                # one PLUS_TIMES hop of the indicator block: the slab
                # rows of A·Q (symmetric graphs only — enforced at
                # ShardedEngine.build — so the slab E IS the slab ET)
                trace_mark()
                y = dist_spmm_ell(PLUS_TIMES, E, mkcol(q))
                return y.blocks[0]

            def fini(X, q_slab):
                # the feature table enters ONCE: this slice's partial
                # [Fp, W] contraction; the router sums partials in
                # slice order (the psum of the unsharded program)
                trace_mark()
                return jnp.dot(
                    X.blocks[0].T, q_slab,
                    preferred_element_type=jnp.float32,
                )

            jh, jf = jax.jit(hop), jax.jit(fini)
            plan.fn = SimpleNamespace(
                hop=lambda q: jh(self.version.E, q),
                fini=lambda q_slab: jf(self.X, q_slab),
            )

        else:
            raise ValueError(f"unsupported sharded kind {kind!r}")

        return plan

    def _sssp_operand(self):
        Ew = self.version.E_weighted
        return Ew if Ew is not None else self.version.E

    # -- slice-resident loop state (round 21) ------------------------------

    def _resident_for(self, kind: str, epoch: int, m: dict, W: int):
        """The resident loop state for this batch epoch.  A ``seed``
        payload (the batch's first fan, or a replay's) re-creates it
        from the payload; otherwise an epoch mismatch means this slice
        respawned mid-batch and holds nothing — a PROTOCOL fact, not a
        death, reported as :class:`StaleEpochError` so the router
        replays the whole batch without quarantining anyone."""
        if m.get("seed"):
            st = self._seed_resident(kind, epoch, m, W)
            self._resident[kind] = st
            return st
        st = self._resident.get(kind)
        if st is None or st.epoch != epoch:
            have = ("no resident state" if st is None
                    else f"epoch {st.epoch}")
            raise StaleEpochError(
                f"slice {self.idx} asked to advance {kind} epoch "
                f"{epoch} but holds {have} (respawned mid-batch?)"
            )
        return st

    def _seed_resident(self, kind: str, epoch: int, m: dict, W: int):
        import jax.numpy as jnp

        if kind == "bfs":
            parents = np.full((self.ls, W), -1, np.int32)
            levels = np.full((self.ls, W), -1, np.int32)
            if "xs" in m:
                sf = m["xs"]
                rows = sf.rows.astype(np.int64)
                keep = (rows >= self.row0) & (rows < self.row1)
                rr = rows[keep] - self.row0
                ll = sf.lanes[keep].astype(np.int64)
                parents[rr, ll] = rows[keep]   # source: self-parent
                levels[rr, ll] = 0
            else:
                slab = np.asarray(m["x"],
                                  np.int32)[self.row0:self.row1]
                rr, ll = np.nonzero(slab >= 0)
                parents[rr, ll] = slab[rr, ll]
                levels[rr, ll] = 0
            return SimpleNamespace(epoch=epoch,
                                   parents=jnp.asarray(parents),
                                   levels=jnp.asarray(levels))
        if kind == "sssp":
            if "ds" in m:
                sf = m["ds"]
                d = np.full((self.ncols, W), np.inf, np.float32)
                d[sf.rows, sf.lanes.astype(np.int64)] = sf.vals
            else:
                d = np.asarray(m["d"], np.float32)
            return SimpleNamespace(epoch=epoch, d=jnp.asarray(d))
        return SimpleNamespace(epoch=epoch, q_slab=None)

    def _bfs_x_operand(self, plan: _SlicePlan, m: dict):
        import jax.numpy as jnp

        if "x" in m:
            return jnp.asarray(np.asarray(m["x"], np.int32))
        sf = m["xs"]
        rows, lanes, _ = _pad_triples(sf, _pow2_cap(sf.nnz),
                                      self.ncols)
        return plan.scatter(rows, lanes)

    # -- the hop surface (one bulk-synchronous step) ----------------------

    def hop(self, kind: str, m: dict) -> dict:
        import jax
        import jax.numpy as jnp

        W = int(m["width"])
        plan = self.plan(kind, W)
        epoch = int(m.get("epoch", 0))
        sparse = m.get("enc") == "sparse"
        t0 = time.perf_counter()
        if kind == "bfs":
            st = self._resident_for(kind, epoch, m, W)
            x = self._bfs_x_operand(plan, m)
            pb, lb, x_next = plan.fn(
                x, st.parents, st.levels, jnp.int32(int(m["level"]))
            )
            plan.executions += 1
            st.parents, st.levels = pb, lb
            xh = np.asarray(jax.device_get(x_next))
            # outbound discovery extraction is slab-LOCAL (a D2H of
            # [ls, W] then nonzero) — never shipped dense when the
            # router asked for triples
            rr, ll = np.nonzero(xh >= 0)
            out = {"any": bool(rr.size), "nnz": int(rr.size)}
            if sparse:
                out["xs"] = SparseFrontier(
                    self.ncols, W, rr.astype(np.int64) + self.row0, ll
                )
            else:
                out["x"] = xh
        elif kind == "sssp":
            st = self._resident_for(kind, epoch, m, W)
            if not m.get("seed"):
                # fold the broadcast relaxations (own included —
                # scatter-MIN is idempotent) into the resident d
                if "ds" in m:
                    sf = m["ds"]
                    if sf.nnz:
                        rows, lanes, vals = _pad_triples(
                            sf, _pow2_cap(sf.nnz), self.ncols
                        )
                        st.d = plan.scatter(st.d, rows, lanes, vals)
                elif "d" in m:
                    st.d = jnp.asarray(np.asarray(m["d"], np.float32))
            nb, ch = plan.fn(st.d)
            plan.executions += 1
            nbh = np.asarray(jax.device_get(nb))
            chh = np.asarray(jax.device_get(ch))
            rr, ll = np.nonzero(chh)
            out = {"any": bool(rr.size), "nnz": int(rr.size)}
            if sparse:
                out["ds"] = SparseFrontier(
                    self.ncols, W, rr.astype(np.int64) + self.row0,
                    ll, nbh[rr, ll]
                )
            else:
                out["d"] = nbh
        elif kind == "propagate":
            st = self._resident_for(kind, epoch, m, W)
            if m.get("final"):
                if st.q_slab is None:
                    # hops==0 edge: the seed rides the final payload
                    st.q_slab = jnp.asarray(
                        _unpack_q(m)[self.row0:self.row1]
                    )
                part = plan.fn.fini(st.q_slab)
                plan.executions += 1
                out = {"partial": np.asarray(jax.device_get(part))}
                self._resident.pop(kind, None)
            else:
                q = jnp.asarray(_unpack_q(m))
                qs = plan.fn.hop(q)
                plan.executions += 1
                # resident q_slab stays EXACT f32 on device for fini;
                # only the wire copy is (optionally) bf16
                st.q_slab = qs
                out = _pack_q_wire(
                    np.asarray(jax.device_get(qs)), m.get("wire")
                )
        else:
            raise ValueError(f"unsupported sharded kind {kind!r}")
        obs.observe("serve.shard.hop_s", time.perf_counter() - t0,
                    kind=kind, slice=self.idx)
        return out

    def collect(self, kind: str, m: dict) -> dict:
        """Fetch the batch's FINAL slab state once, after the hop loop
        converges (round 21) — replaces the per-hop dense state
        round-trips of round 20.  Pops the resident entry: a hop under
        the same epoch afterwards is a protocol bug and correctly
        raises :class:`StaleEpochError`."""
        import jax

        epoch = int(m.get("epoch", 0))
        st = self._resident.get(kind)
        if st is None or st.epoch != epoch:
            have = ("no resident state" if st is None
                    else f"epoch {st.epoch}")
            raise StaleEpochError(
                f"slice {self.idx} asked to collect {kind} epoch "
                f"{epoch} but holds {have}"
            )
        self._resident.pop(kind, None)
        if kind == "bfs":
            return {
                "parents": np.asarray(jax.device_get(st.parents)),
                "levels": np.asarray(jax.device_get(st.levels)),
            }
        if kind == "sssp":
            d = np.asarray(jax.device_get(st.d))
            return {"d": d[self.row0:self.row1]}
        raise ValueError(f"kind {kind!r} holds no collectable state")

    def _scatter_caps(self, width: int) -> list:
        """Every pow2 scatter-capacity bucket a frontier of up to
        ``ncols * width`` triples can land in."""
        caps = []
        cap = SCATTER_CAP_FLOOR
        top = _pow2_cap(self.ncols * int(width))
        while cap <= top:
            caps.append(cap)
            cap <<= 1
        return caps

    def warmup(self, kinds=None, widths=None) -> dict:
        """Pre-trace every (kind, width) hop program AND every pow2
        scatter-capacity bucket on inert all-pad steps (empty frontier
        / all-inf distances / zero indicator) — after this, serving
        inside the warmed set performs ZERO traces under ANY encoding
        (asserted over IPC by the bench)."""
        kinds = self.kinds if kinds is None else tuple(kinds)
        widths = (1, 2, 4, 8, 16) if widths is None else tuple(widths)
        out = {}
        for kind in kinds:
            for w in sorted(set(int(x) for x in widths)):
                t0 = time.perf_counter()
                plan = self.plan(kind, w)
                if kind == "bfs":
                    self.hop(kind, {
                        "width": w, "epoch": 0, "seed": True,
                        "enc": "sparse", "level": 0,
                        "xs": SparseFrontier(
                            self.ncols, w, np.zeros(0, np.int32),
                            np.zeros(0, np.uint8),
                        ),
                    })
                    for cap in self._scatter_caps(w):
                        plan.scatter(
                            np.full(cap, self.ncols, np.int32),
                            np.zeros(cap, np.int32),
                        )
                elif kind == "sssp":
                    self.hop(kind, {
                        "width": w, "epoch": 0, "seed": True,
                        "enc": "sparse",
                        "ds": SparseFrontier(
                            self.ncols, w, np.zeros(0, np.int32),
                            np.zeros(0, np.uint8),
                            np.zeros(0, np.float32),
                        ),
                    })
                    st = self._resident[kind]
                    for cap in self._scatter_caps(w):
                        st.d = plan.scatter(
                            st.d,
                            np.full(cap, self.ncols, np.int32),
                            np.zeros(cap, np.int32),
                            np.zeros(cap, np.float32),
                        )
                else:
                    q = np.zeros((self.ncols, w), np.float32)
                    self.hop(kind, {"width": w, "epoch": 0,
                                    "seed": True, "q": q,
                                    "wire": "f32"})
                    self.hop(kind, {"width": w, "epoch": 0,
                                    "final": True})
                out[(kind, w)] = time.perf_counter() - t0
        self._resident.clear()
        return out

    def trace_mark(self) -> int:
        with self._lock:
            return sum(p.traces for p in self._plans.values())

    # -- the write lane (two-phase, per-slice WAL) ------------------------

    def wal_begin(self, first_seq: int, rows, cols, vals,
                  op_codes) -> dict:
        """Phase 1: durably append the FULL batch (global coordinates)
        to this slice's log — the per-slice sequence line stays
        contiguous with the global one, so the vector frontier is
        comparable across slices."""
        if self.wal is None:
            raise ValueError("slice has no WAL (built without home=)")
        off = self.wal.append(first_seq, rows, cols, vals, op_codes)
        obs.count("serve.shard.wal_appends", slice=self.idx)
        return {"offset": int(off), "wal_seq": int(self.wal.position())}

    def wal_abort(self, first_seq: int, last_seq: int) -> dict:
        """Tombstone a range whose coordinated append failed on a
        SIBLING slice — replay must not resurrect a write whose future
        was failed (the round-16 drop-record semantics)."""
        if self.wal is not None:
            self.wal.append_drop(first_seq, last_seq)
        obs.count("serve.shard.wal_aborts", slice=self.idx)
        return {"dropped": [int(first_seq), int(last_seq)]}

    def wal_commit(self, m: dict) -> dict:
        """Phase 2: apply the slab's sub-batch and stamp the slice
        frontier.  IDEMPOTENT: a batch at-or-below the current
        frontier was already folded in (recovery replay, or a re-sent
        commit after a heal) — report the current state, change
        nothing.  An empty sub-batch (no rows in this slab) still
        advances the frontier: the vector stays comparable."""
        from ..dynamic import merge as dyn_merge

        first, last = int(m["first_seq"]), int(m["last_seq"])
        if int(self.version.wal_seq) >= last:
            return self._commit_summary(applied=0)
        rows = np.asarray(m["rows"], np.int64)
        mask = (rows >= self.row0) & (rows < self.row1)
        t0 = time.perf_counter()
        if mask.any():
            sub = DeltaBatch(
                rows=rows[mask] - self.row0,
                cols=np.asarray(m["cols"], np.int64)[mask],
                vals=np.asarray(m["vals"], np.float32)[mask],
                ops=np.asarray(m["ops"], np.int8)[mask],
                first_seq=first, last_seq=last, oldest_at=0.0,
            )
            build_kinds = tuple(
                k for k in self.kinds if k in ("bfs", "sssp")
            ) or ("bfs",)
            version = dyn_merge.apply_delta(
                self.version, sub, kinds=build_kinds, grid=self.grid
            )
            version.wal_seq = last
            version.vid = self.version.vid + 1
            self.version = version
            self.swaps += 1
            applied = int(mask.sum())
        else:
            self.version.wal_seq = last
            applied = 0
        obs.observe("serve.shard.merge_s", time.perf_counter() - t0,
                    slice=self.idx)
        obs.count("serve.shard.commits", slice=self.idx)
        self._commits_since_ckpt += 1
        if (self.checkpoint_every
                and self._commits_since_ckpt >= self.checkpoint_every):
            try:
                self.checkpoint_now(reason="auto")
            except Exception:
                obs.count("serve.shard.checkpoint_failed",
                          slice=self.idx)
        return self._commit_summary(applied=applied)

    def _commit_summary(self, applied: int) -> dict:
        return {
            "wal_seq": int(self.version.wal_seq),
            "nnz": int(self.version.nnz),
            "vid": int(self.version.vid),
            "applied": int(applied),
        }

    # -- snapshots ---------------------------------------------------------

    def checkpoint_now(self, reason: str = "manual") -> dict:
        """Slab snapshot at this slice's frontier + retention prune +
        WAL truncation through the oldest retained stamp (the
        ``Server.checkpoint_now`` policy, per slice).  The slab X is
        stripped (its restore path assumes a square table); features
        live in ``features.npy`` beside the snapshots."""
        if self.home is None:
            raise ValueError("slice has no durability home")
        seq = int(self.version.wal_seq)
        path = os.path.join(self.home, ckpt.snapshot_name(seq))
        v = self.version
        if v.X is not None:
            v = dataclasses.replace(v, X=None, feat_dim=0)
        ckpt.save_version(path, v, extra_meta={"shard": {
            "idx": self.idx, "row0": self.row0, "row1": self.row1,
            "nrows": self.nrows, "ncols": self.ncols,
        }})
        snaps = ckpt.list_snapshots(self.home)
        for old in snaps[:-self.checkpoint_retain]:
            try:
                os.unlink(old)
            except OSError:
                pass
        snaps = ckpt.list_snapshots(self.home)
        if self.wal is not None and snaps:
            self.wal.truncate(ckpt.snapshot_seq(snaps[0]))
        obs.count("serve.shard.checkpoints", slice=self.idx,
                  reason=reason)
        return {"path": path, "wal_seq": seq, "reason": reason}

    # -- introspection -----------------------------------------------------

    def to_host_coo(self) -> dict:
        """The slab edges in GLOBAL coordinates (rows translated back)
        — the router concatenates and key-sorts slices into the same
        (rows, cols, weights) triple an unsharded
        ``keep_coo=True`` engine retains (bit-exact recovery gate)."""
        if self.version.host_coo is None:
            raise ValueError("slab was built without keep_coo")
        rows, cols, _nc = self.version.host_coo
        w = self.version.host_weights
        return {
            "rows": np.asarray(rows, np.int64) + self.row0,
            "cols": np.asarray(cols, np.int64),
            "weights": (None if w is None
                        else np.asarray(w, np.float32)),
        }

    def stats(self) -> dict:
        with self._lock:
            plans = {
                f"{k}/{w}": {"traces": p.traces,
                             "executions": p.executions}
                for (k, w), p in sorted(self._plans.items())
            }
        return {
            "slice": self.idx,
            "rows": [self.row0, self.row1],
            "nnz": int(self.version.nnz),
            "vid": int(self.version.vid),
            "wal_seq": int(self.version.wal_seq),
            "device_bytes": self.device_bytes(),
            "plans": plans,
            "plan_hits": self.plan_hits,
            "plan_misses": self.plan_misses,
            "swaps": self.swaps,
            "traces": self.trace_mark(),
            "wal": None if self.wal is None else self.wal.stats(),
        }

    def device_bytes(self) -> int:
        total = self.version.device_bytes()
        if self.X is not None:
            total += int(self.X.blocks.nbytes)
        return total

    def close(self) -> None:
        if self.wal is not None:
            self.wal.close()


def dispatch_slice_op(rt: SliceRuntime, op: str, m: dict):
    """The slice op surface, shared VERBATIM by the in-process handle
    and the subprocess worker (one protocol, two transports)."""
    if op == "hop":
        return rt.hop(m["kind"], m)
    if op == "collect":
        return rt.collect(m["kind"], m)
    if op == "warmup":
        w = rt.warmup(kinds=m.get("kinds"), widths=m.get("widths"))
        return {f"{k}/{wd}": s for (k, wd), s in w.items()}
    if op == "wal_begin":
        return rt.wal_begin(
            int(m["first_seq"]), m["rows"], m["cols"], m["vals"],
            m["ops"],
        )
    if op == "wal_commit":
        return rt.wal_commit(m)
    if op == "wal_abort":
        return rt.wal_abort(int(m["first_seq"]), int(m["last_seq"]))
    if op == "checkpoint_now":
        return rt.checkpoint_now(reason=m.get("reason", "manual"))
    if op == "to_host_coo":
        return rt.to_host_coo()
    if op == "stats":
        return rt.stats()
    if op == "trace_mark":
        return {"mark": rt.trace_mark()}
    if op == "device_bytes":
        return {"bytes": rt.device_bytes()}
    if op == "ping":
        return {"pong": True, "slice": rt.idx}
    raise ValueError(f"unknown slice op {op!r}")


# --------------------------------------------------------------------------
# parent-side slice handles
# --------------------------------------------------------------------------


class LocalSlice:
    """In-process slice handle — the fast tier-1 representative (no
    subprocess, no IPC; ``kill()`` simulates a crash by dropping the
    runtime WITHOUT flushing anything, the honest analog of SIGKILL
    given the WAL's append-before-ack contract)."""

    def __init__(self, factory, idx: int):
        self.idx = int(idx)
        self._factory = factory
        self.rt: SliceRuntime | None = factory(recover=False)
        self.quarantined = False

    @property
    def pid(self) -> int:
        return os.getpid()

    def call(self, op: str, payload: dict | None = None,
             timeout_s: float | None = None):
        rt = self.rt
        if rt is None or self.quarantined:
            raise ReplicaDeadError(
                f"slice {self.idx} is out of service"
            )
        return dispatch_slice_op(rt, op, payload or {})

    def rpc(self, op: str, payload: dict | None = None,
            timeout_s: float | None = None) -> Future:
        fut: Future = Future()
        try:
            fut.set_result(self.call(op, payload, timeout_s))
        except Exception as e:
            fut.set_exception(e)
        return fut

    def is_serving(self) -> bool:
        return self.rt is not None and not self.quarantined

    def heartbeat_age(self) -> float:
        return 0.0

    def kill(self) -> None:
        """Crash simulation: the runtime vanishes mid-flight; the WAL
        fd is abandoned un-flushed (appends already hit disk — the
        durability contract under test)."""
        self.rt = None

    def quarantine(self, exc: Exception) -> int:
        self.quarantined = True
        self.rt = None
        return 0

    def respawn(self) -> "LocalSlice":
        return LocalSlice.__new_from(self._factory, self.idx)

    @classmethod
    def __new_from(cls, factory, idx):
        sl = cls.__new__(cls)
        sl.idx = idx
        sl._factory = factory
        sl.rt = factory(recover=True)
        sl.quarantined = False
        return sl

    def close(self) -> None:
        if self.rt is not None:
            self.rt.close()
            self.rt = None


class ProcSlice:
    """Subprocess slice handle: one ``_shardworker`` child with its
    OWN JAX runtime, driven through a ``ReplicaProc`` (futures,
    heartbeat tracking, deadline sweep, quarantine — the procfleet
    machinery pointed at a slice instead of a whole replica)."""

    def __init__(self, idx: int, boot: dict, *, workdir: str,
                 devices: int = 1, hb_interval_s: float = 0.25,
                 ipc_timeout_s: float = 60.0,
                 boot_timeout_s: float = 300.0):
        self.idx = int(idx)
        self._boot_msg = dict(boot)
        self._workdir = workdir
        self._devices = int(devices)
        self._hb_interval_s = float(hb_interval_s)
        self._ipc_timeout_s = float(ipc_timeout_s)
        self._boot_timeout_s = float(boot_timeout_s)
        self.rp = self._launch()
        self.boot_info = self.rp.call(
            "boot", self._boot_msg, timeout_s=self._boot_timeout_s
        )
        # the boot reply is proof of life, but the child only starts
        # its heartbeat thread AFTER boot — stamp the heartbeat clock
        # here so the hang detector measures from boot completion, not
        # process launch (a warm boot longer than hb_timeout_s must
        # not read as an already-hung slice and respawn forever)
        self.rp.last_hb = {
            "pid": self.boot_info.get("pid"), "depth": 0,
            "serving": True, "slice": self.idx,
        }
        self.rp.last_hb_t = time.monotonic()

    def _child_env(self) -> dict:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count={self._devices}"
        )
        env["COMBBLAS_WAL"] = "0"
        env["COMBBLAS_OBS"] = "1" if obs.ENABLED else "0"
        import combblas_tpu

        pkg_root = os.path.dirname(os.path.dirname(
            os.path.abspath(combblas_tpu.__file__)
        ))
        pp = env.get("PYTHONPATH")
        env["PYTHONPATH"] = (
            pkg_root if not pp else pkg_root + os.pathsep + pp
        )
        return env

    def _launch(self) -> ReplicaProc:
        parent_sock, child_sock = socket.socketpair()
        log = open(
            os.path.join(self._workdir, f"slice{self.idx}.log"), "ab"
        )
        try:
            proc = subprocess.Popen(
                [
                    sys.executable, "-m",
                    "combblas_tpu.serve._shardworker",
                    "--fd", str(child_sock.fileno()),
                ],
                pass_fds=(child_sock.fileno(),),
                env=self._child_env(),
                stdout=log, stderr=subprocess.STDOUT,
                start_new_session=True,  # chaos signals hit the
                # slice, never the router's process group
            )
        finally:
            log.close()
            child_sock.close()
        return ReplicaProc(
            self.idx, proc,
            Channel(parent_sock, peer=f"slice{self.idx}"),
            tenant=f"slice{self.idx}",
            ipc_timeout_s=self._ipc_timeout_s,
        )

    @property
    def pid(self) -> int | None:
        return self.rp.proc.pid if self.rp.proc is not None else None

    def call(self, op: str, payload: dict | None = None,
             timeout_s: float | None = None):
        return self.rp.call(op, payload, timeout_s=timeout_s)

    def rpc(self, op: str, payload: dict | None = None,
            timeout_s: float | None = None) -> Future:
        return self.rp.rpc(op, payload, timeout_s=timeout_s)

    def is_serving(self) -> bool:
        return self.rp.is_serving()

    def heartbeat_age(self) -> float:
        return self.rp.heartbeat_age()

    def kill(self) -> None:
        self.rp.signal(signal.SIGKILL)

    def signal(self, sig: int) -> None:
        self.rp.signal(sig)

    def quarantine(self, exc: Exception) -> int:
        return self.rp.quarantine(exc)

    def respawn(self) -> "ProcSlice":
        boot = dict(self._boot_msg)
        # respawn recovers from the slice home: the slab COO never
        # crosses the wire twice
        for k in ("rows", "cols", "weights", "features"):
            boot.pop(k, None)
        boot["recover"] = True
        return ProcSlice(
            self.idx, boot, workdir=self._workdir,
            devices=self._devices, hb_interval_s=self._hb_interval_s,
            ipc_timeout_s=self._ipc_timeout_s,
            boot_timeout_s=self._boot_timeout_s,
        )

    def close(self) -> None:
        self.rp.close()


# --------------------------------------------------------------------------
# the sharded engine (router)
# --------------------------------------------------------------------------


class ShardedGraphVersion:
    """The router-side view of the CURRENT sharded generation: the
    manifest facts plus the per-slice frontier VECTOR.  Duck-types the
    ``GraphVersion`` surface ``serve/api.py`` reads (``ncols``/
    ``nnz``/``wal_seq``/``vid``/``dyn.last_stats``); the scalar
    ``wal_seq`` is the vector MINIMUM — the only safe scalar
    projection (everything at-or-below it is durable AND applied on
    every slice)."""

    def __init__(self, *, nrows: int, ncols: int, nnz: int,
                 bounds, frontier, device_bytes=None,
                 merge_stats=None):
        self.nrows = int(nrows)
        self.ncols = int(ncols)
        self.nnz = int(nnz)
        self.bounds = tuple(tuple(b) for b in bounds)
        self.frontier = [int(s) for s in frontier]
        self.wal_seq = min(self.frontier) if self.frontier else -1
        self.device_bytes_per_slice = list(device_bytes or [])
        self.vid = 0
        self.host_coo = None  # assembled on demand via the engine
        self.dyn = SimpleNamespace(last_stats=SimpleNamespace(
            mode=(merge_stats or {}).get("mode", "sharded"),
            latency_s=(merge_stats or {}).get("latency_s", 0.0),
        ))

    @property
    def nslices(self) -> int:
        return len(self.bounds)

    def device_bytes(self) -> int:
        """MAX per-slice resident bytes — the per-host capacity number
        the ~1/p scaling claim is measured on (a sharded service is
        capacity-bound by its fullest host, not the sum)."""
        return max(self.device_bytes_per_slice, default=0)


class ShardedEngine:
    """N slices served as ONE engine — the ``GraphEngine`` duck-type
    ``serve/api.py`` drives (module docstring).  Durability is
    engine-owned: ``Server`` skips its scalar WAL attachment
    (``owns_durability``) and routes ``apply_delta`` through the
    two-phase per-slice protocol."""

    owns_durability = True
    supports_updates = True

    def __init__(self, slices, spec: ShardSpec, kinds, *, home: str,
                 nnz: int, feat_dim: int = 0,
                 max_iters: int | None = None,
                 propagate_hops: int = 2,
                 hb_timeout_s: float = 3.0,
                 ipc_timeout_s: float = 60.0,
                 recover_wait_s: float = 30.0,
                 exec_retries: int = 3,
                 frontier: str | None = None,
                 density: float | None = None,
                 wire: str | None = None,
                 factories=None):
        self.slices = list(slices)
        self.spec = spec
        self._kinds = tuple(kinds)
        self.home = home
        self.nrows = spec.nrows
        self.max_iters = max_iters
        self.propagate_hops = int(propagate_hops)
        self.feat_dim = int(feat_dim)
        self.hb_timeout_s = float(hb_timeout_s)
        self.ipc_timeout_s = float(ipc_timeout_s)
        self.recover_wait_s = float(recover_wait_s)
        self.exec_retries = int(exec_retries)
        # round-21 wire-protocol knobs: the ENCODING IS A ROUTER
        # DECISION stamped into every hop payload — slices never
        # branch at trace time on it
        self.frontier_mode = tuner_config.shard_frontier(frontier)
        self.density_threshold = tuner_config.shard_density(density)
        self.wire = tuner_config.shard_wire(wire)
        self._epoch = 0
        self.last_exec_stats: dict = {}
        self._factories = list(factories or [])
        self._exec_lock = threading.RLock()
        self._write_lock = threading.Lock()
        self._sup_lock = threading.RLock()
        self._needs_rebuild: set[int] = set()
        self._replace_next: dict[int, float] = {}
        self._replace_backoff: dict[int, float] = {}
        self.replacements = 0
        self.respawn_failures = 0
        self.swaps = 0
        self._sup_stop = threading.Event()
        self._sup_thread = None
        # trace accounting across respawns: floor = a slice's counter
        # right after (re)boot warmup, so warmup traces never count as
        # serving retraces; a dead slice's last-known delta folds into
        # the lost base so marks stay monotone
        self._trace_floor: dict[int, int] = {}
        self._last_mark: dict[int, int] = {}
        self._trace_lost = 0
        frontier, nnzs, bytes_ = self._poll_slices()
        self._version = ShardedGraphVersion(
            nrows=spec.nrows, ncols=spec.ncols,
            nnz=int(nnz if nnz >= 0 else sum(nnzs)),
            bounds=spec.bounds, frontier=frontier,
            device_bytes=bytes_,
        )
        for i, sl in enumerate(self.slices):
            self._floor_traces(i, sl)
        obs.gauge("serve.shard.slices", len(self.slices))

    # -- construction ------------------------------------------------------

    @classmethod
    def build(cls, rows, cols, *, nrows: int, nslices: int = 2,
              ncols: int | None = None, weights=None, kinds=None,
              features=None, symmetric: bool = False,
              home: str | None = None, mode: str = "local",
              warmup: bool = True, warmup_widths=None,
              headroom=None, max_iters=None, propagate_hops: int = 2,
              fsync: str | None = None, checkpoint_every: int = 0,
              checkpoint_retain: int = 2,
              hb_interval_s: float = 0.25, hb_timeout_s: float = 3.0,
              ipc_timeout_s: float = 60.0,
              recover_wait_s: float = 30.0,
              frontier: str | None = None,
              density: float | None = None,
              wire: str | None = None) -> "ShardedEngine":
        """Partition a global COO over ``nslices`` row slabs and boot
        one slice per slab (``mode="local"`` in-process — the tier-1
        representative; ``mode="process"`` real subprocesses).  The
        global dedup/min-combine happens per slab — row slabs are
        key-disjoint, so the result is identical to the unsharded
        build (the bit-exactness base case)."""
        n = int(nrows)
        nc = int(ncols) if ncols is not None else n
        if kinds is None:
            kinds = ("bfs",)
            if weights is not None:
                kinds += ("sssp",)
            if features is not None and symmetric:
                kinds += ("propagate",)
        kinds = tuple(kinds)
        bad = [k for k in kinds if k not in SHARDED_KINDS]
        if bad:
            raise ValueError(
                f"kinds {bad} do not decompose into row-slab hops "
                f"(sharded kinds: {SHARDED_KINDS})"
            )
        if "propagate" in kinds:
            if not symmetric:
                raise ValueError(
                    "sharded 'propagate' needs symmetric=True: the "
                    "hop operator must be its own transpose for the "
                    "slab matrix to serve both orientations"
                )
            if features is None:
                raise ValueError("'propagate' needs features=")
        home = home or tempfile.mkdtemp(prefix="combblas-shard-")
        os.makedirs(home, exist_ok=True)
        spec = plan_partition(n, int(nslices), ncols=nc)
        rows = np.asarray(rows)
        cols = np.asarray(cols)
        slices = []
        factories = []
        nnz_total = 0
        for i in range(spec.nslices):
            r0, r1 = spec.bounds[i]
            lrows, lcols, lw = shard_coo(spec, i, rows, cols, weights)
            shome = os.path.join(home, f"slice{i}")
            os.makedirs(shome, exist_ok=True)
            if mode == "local":
                factory = _local_factory(
                    i, r0, r1, n, nc, lrows, lcols, lw, kinds,
                    features=features, headroom=headroom, home=shome,
                    fsync=fsync, max_iters=max_iters,
                    propagate_hops=propagate_hops,
                    checkpoint_every=checkpoint_every,
                    checkpoint_retain=checkpoint_retain,
                    warmup=warmup, warmup_widths=warmup_widths,
                )
                sl = LocalSlice(factory, i)
                nnz_total += int(sl.rt.version.nnz)
            elif mode == "process":
                boot = {
                    "idx": i, "row0": r0, "row1": r1,
                    "nrows": n, "ncols": nc,
                    "rows": np.asarray(lrows, np.int64),
                    "cols": np.asarray(lcols, np.int64),
                    "weights": (None if lw is None
                                else np.asarray(lw, np.float32)),
                    "kinds": list(kinds),
                    "features": (
                        None if features is None else
                        np.asarray(features, np.float32)[r0:r1]
                    ),
                    "home": shome, "fsync": fsync,
                    "max_iters": max_iters,
                    "propagate_hops": propagate_hops,
                    "checkpoint_every": checkpoint_every,
                    "checkpoint_retain": checkpoint_retain,
                    "warmup": bool(warmup),
                    "warmup_widths": (
                        None if warmup_widths is None
                        else list(warmup_widths)
                    ),
                    "hb_interval_s": hb_interval_s,
                    "recover": False,
                }
                sl = ProcSlice(
                    i, boot, workdir=home,
                    hb_interval_s=hb_interval_s,
                    ipc_timeout_s=ipc_timeout_s,
                )
                nnz_total += int(sl.boot_info["nnz"])
                factory = None
            else:
                raise ValueError(f"unknown shard mode {mode!r}")
            slices.append(sl)
            factories.append(factory)
        if mode == "local" and warmup:
            for sl in slices:
                sl.call("warmup", {"widths": warmup_widths})
        eng = cls(
            slices, spec, kinds, home=home, nnz=nnz_total,
            feat_dim=(0 if features is None
                      else int(np.asarray(features).shape[1])),
            max_iters=max_iters, propagate_hops=propagate_hops,
            hb_timeout_s=hb_timeout_s, ipc_timeout_s=ipc_timeout_s,
            recover_wait_s=recover_wait_s, frontier=frontier,
            density=density, wire=wire, factories=factories,
        )
        eng.mode = mode
        eng._write_manifest()
        return eng

    @classmethod
    def recover(cls, home: str, *, mode: str = "local",
                max_iters=None, hb_interval_s: float = 0.25,
                hb_timeout_s: float = 3.0,
                ipc_timeout_s: float = 60.0,
                recover_wait_s: float = 30.0,
                frontier: str | None = None,
                density: float | None = None,
                wire: str | None = None) -> "ShardedEngine":
        """Reboot the whole service from its home: manifest → slice
        homes → per-slice snapshot + WAL-suffix replay.  Each slice
        recovers to ITS OWN frontier (the vector semantics); the
        scalar view re-converges at the minimum."""
        with open(os.path.join(home, MANIFEST_NAME)) as f:
            man = json.load(f)
        if man.get("v") != MANIFEST_SCHEMA:
            raise dyn_wal.RecoveryError(
                f"manifest schema {man.get('v')!r} != "
                f"{MANIFEST_SCHEMA!r}"
            )
        kinds = tuple(man["kinds"])
        spec = ShardSpec(
            nrows=int(man["nrows"]), ncols=int(man["ncols"]),
            bounds=tuple(tuple(b) for b in man["bounds"]),
        )
        slices = []
        factories = []
        for i in range(spec.nslices):
            shome = os.path.join(home, f"slice{i}")
            if mode == "local":
                factory = _local_recover_factory(
                    i, shome, kinds, max_iters=max_iters,
                    propagate_hops=int(man.get("propagate_hops", 2)),
                )
                sl = LocalSlice.__new__(LocalSlice)
                sl.idx = i
                sl._factory = factory
                sl.rt = factory(recover=True)
                sl.quarantined = False
            else:
                boot = {
                    "idx": i, "home": shome, "kinds": list(kinds),
                    "recover": True, "max_iters": max_iters,
                    "propagate_hops": int(
                        man.get("propagate_hops", 2)
                    ),
                    "warmup": True,
                    "hb_interval_s": hb_interval_s,
                }
                sl = ProcSlice(
                    i, boot, workdir=home,
                    hb_interval_s=hb_interval_s,
                    ipc_timeout_s=ipc_timeout_s,
                )
                factory = None
            slices.append(sl)
            factories.append(factory)
        eng = cls(
            slices, spec, kinds, home=home, nnz=-1,
            feat_dim=int(man.get("feat_dim", 0)),
            max_iters=max_iters,
            propagate_hops=int(man.get("propagate_hops", 2)),
            hb_timeout_s=hb_timeout_s, ipc_timeout_s=ipc_timeout_s,
            recover_wait_s=recover_wait_s, frontier=frontier,
            density=density, wire=wire, factories=factories,
        )
        eng.mode = mode
        return eng

    def _write_manifest(self) -> None:
        """Atomic manifest write: the service's self-description +
        the current frontier VECTOR (advisory — each slab snapshot is
        self-describing; recovery trusts the per-slice files for the
        frontier truth and the manifest for the shape)."""
        man = {
            "v": MANIFEST_SCHEMA,
            "nrows": self.spec.nrows, "ncols": self.spec.ncols,
            "nslices": self.spec.nslices,
            "bounds": [list(b) for b in self.spec.bounds],
            "kinds": list(self._kinds),
            "feat_dim": self.feat_dim,
            "propagate_hops": self.propagate_hops,
            "frontier": list(self._version.frontier)
            if getattr(self, "_version", None) is not None else [],
        }
        path = os.path.join(self.home, MANIFEST_NAME)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(man, f, indent=1)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    # -- GraphEngine duck-type surface ------------------------------------

    @property
    def version(self) -> ShardedGraphVersion:
        return self._version

    @property
    def version_id(self) -> int:
        return self._version.vid

    def kinds(self) -> tuple:
        return self._kinds

    @property
    def plan_misses(self) -> int:
        return 0  # slices own their plan caches; see stats()["shard"]

    def serve(self, config=None, tenant: str | None = None):
        from .api import Server
        from .scheduler import ServeConfig

        return Server(self, config or ServeConfig(), tenant=tenant)

    def build_version(self, *a, **kw):
        raise NotImplementedError(
            "a sharded engine rebuilds through its slices; use "
            "apply_delta (the write lane) or rebuild with "
            "ShardedEngine.build"
        )

    def swap(self, version) -> float:
        t0 = time.perf_counter()
        with self._exec_lock:
            version.vid = self._version.vid + 1
            self._version = version
            self.swaps += 1
        dt = time.perf_counter() - t0
        obs.gauge("serve.shard.frontier_min", version.wal_seq)
        return dt

    def warmup(self, kinds=None, widths=None) -> dict:
        out: dict = {}
        payload = {
            "kinds": list(kinds) if kinds else None,
            "widths": list(widths) if widths else None,
        }
        futs = [
            (sl, sl.rpc("warmup", payload,
                        timeout_s=self.ipc_timeout_s * 4))
            for sl in self.slices
        ]
        for sl, f in futs:
            for kw, s in f.result(
                timeout=self.ipc_timeout_s * 4 + 5
            ).items():
                k, w = kw.rsplit("/", 1)
                key = (k, int(w))
                out[key] = max(out.get(key, 0.0), float(s))
        for i, sl in enumerate(self.slices):
            self._floor_traces(i, sl)
        return out

    # -- trace accounting --------------------------------------------------

    def _slice_mark(self, i: int, sl) -> int:
        try:
            m = int(sl.call("trace_mark", timeout_s=30.0)["mark"])
            self._last_mark[i] = m
            return m
        except Exception:
            return self._last_mark.get(i, self._trace_floor.get(i, 0))

    def _floor_traces(self, i: int, sl) -> None:
        m = self._slice_mark(i, sl)
        self._trace_floor[i] = m
        self._last_mark[i] = m

    def trace_mark(self) -> int:
        total = self._trace_lost
        for i, sl in enumerate(self.slices):
            m = self._slice_mark(i, sl)
            total += max(0, m - self._trace_floor.get(i, 0))
        return total

    def retraces_since(self, mark: int) -> int:
        return self.trace_mark() - mark

    # -- execution (the router hop loop) ----------------------------------

    def _mint_epoch(self) -> int:
        """A fresh batch-attempt token (under the exec lock): every
        hop of one attempt carries it, slices key their resident loop
        state on it, and a replay gets a NEW one — so state left by a
        failed attempt can never leak into its replay."""
        self._epoch += 1
        return self._epoch

    def _choose_enc(self, nnz: int, W: int) -> str:
        """The per-hop encoding decision (router-owned; slices obey
        the stamped choice): triples win while the frontier is sparse,
        the dense operand wins once scatter padding + triple overhead
        pass the density threshold (the diropt precedent — a DATA
        decision, never a trace-time branch)."""
        if self.frontier_mode != "auto":
            return self.frontier_mode
        dense = self.spec.ncols * int(W)
        return ("sparse"
                if nnz <= self.density_threshold * dense else "dense")

    def _pack_q_payload(self, q: np.ndarray) -> dict:
        p = _pack_q_wire(q, self.wire)
        if self.wire == "bf16":
            err = (float(np.max(np.abs(unpack_bf16(p["q"]) - q)))
                   if q.size else 0.0)
            obs.observe("serve.shard.wire_quant_err", err)
        return p

    def execute(self, kind: str, sources) -> dict:
        """One batch, bulk-synchronously across slices; on a slice
        failure mid-batch the whole batch replays after the heal
        (replay is idempotent: a fresh epoch re-seeds every slice's
        resident state — including the respawned one's, which is how
        a StaleEpochError report is resolved)."""
        last_exc = None
        for attempt in range(self.exec_retries + 1):
            if attempt:
                obs.count("serve.shard.exec_retries", kind=kind)
                self._heal()
            try:
                with self._exec_lock, obs.span(
                    "serve.shard.batch", kind=kind,
                    width=int(np.asarray(sources).shape[0]),
                ):
                    return self._execute_once(kind, sources)
            except (ReplicaDeadError, IpcTimeoutError,
                    ConnectionError, StaleEpochError) as e:
                last_exc = e
        raise RuntimeError(
            f"sharded {kind} batch failed after "
            f"{self.exec_retries + 1} attempts: {last_exc}"
        ) from last_exc

    def _fan_hop(self, kind: str, per_slice_payload, *,
                 op: str = "hop", enc: str | None = None,
                 stats: dict | None = None) -> list:
        """One bulk-synchronous fan (``hop`` or ``collect``): RPC
        every slice in parallel, gather in slice order, account the
        wire bytes both directions.  A transport/death failure
        quarantines the slice (sticky — the supervisor respawns it)
        and raises; a :class:`StaleEpochError` is a HEALTHY slice
        reporting lost resident state — re-raised for a whole-batch
        replay WITHOUT quarantining the reporter."""
        t0 = time.perf_counter()
        enc_label = enc if enc is not None else op
        bytes_out = 0
        futs = []
        for i, sl in enumerate(self.slices):
            payload = per_slice_payload(i)
            bytes_out += _payload_nbytes(payload)
            try:
                futs.append(sl.rpc(
                    op, payload, timeout_s=self.ipc_timeout_s,
                ))
            except Exception as e:
                self._mark_dead(i, e)
                raise
        results = []
        failed = None
        stale = None
        for i, f in enumerate(futs):
            try:
                results.append(f.result(
                    timeout=self.ipc_timeout_s + 5
                ))
            except StaleEpochError as e:
                stale = stale or e
                results.append(None)
            except Exception as e:
                self._mark_dead(i, e)
                failed = failed or e
                results.append(None)
        if failed is not None:
            # a real death outranks a stale report: heal first, the
            # replay re-seeds everyone anyway
            if isinstance(failed, (ReplicaDeadError, IpcTimeoutError,
                                   ConnectionError)):
                raise failed
            raise ReplicaDeadError(str(failed)) from failed
        if stale is not None:
            obs.count("serve.shard.stale_epochs", kind=kind)
            raise stale
        bytes_in = sum(_payload_nbytes(r) for r in results)
        obs.count("serve.shard.hop_bytes", bytes_out,
                  direction="out", encoding=enc_label)
        obs.count("serve.shard.hop_bytes", bytes_in,
                  direction="in", encoding=enc_label)
        if op == "hop":
            obs.count("serve.shard.hops", kind=kind)
            if enc in ("sparse", "dense"):
                obs.count("serve.shard.encoding", choice=enc)
        if stats is not None:
            stats["hops" if op == "hop" else "collects"] += 1
            stats["bytes_out"] += bytes_out
            stats["bytes_in"] += bytes_in
            by = stats["bytes_by_enc"]
            by[enc_label] = by.get(enc_label, 0) + bytes_out + bytes_in
            if op == "hop" and enc in ("sparse", "dense"):
                eh = stats["enc_hops"]
                eh[enc] = eh.get(enc, 0) + 1
            stats["hop_wall_s"] += time.perf_counter() - t0
        return results

    def _execute_once(self, kind: str, sources) -> dict:
        sources = np.asarray(sources, np.int32)
        from ..models import PAD_ROOT

        W = int(sources.shape[0])
        n = self.nrows
        nc = self.spec.ncols
        bounds = self.spec.bounds
        live = sources != PAD_ROOT
        lanes = np.arange(W)
        valid = live & (sources >= 0) & (sources < n)
        epoch = self._mint_epoch()
        stats = {
            "kind": kind, "width": W, "epoch": epoch,
            "hops": 0, "collects": 0,
            "bytes_out": 0, "bytes_in": 0,
            "bytes_by_enc": {}, "enc_hops": {},
            "frontier_nnz": [], "hop_wall_s": 0.0,
        }
        self.last_exec_stats = stats
        if kind == "bfs":
            # the router-side mirror of _bfs_batch_impl's init + loop:
            # the step always runs at least once (active starts True);
            # continue iff any slice discovered new vertices and the
            # level count is under the cap — identical niter semantics
            iters = self.max_iters if self.max_iters is not None \
                else n
            sf = SparseFrontier(
                nc, W, sources[valid], lanes[valid].astype(np.uint8)
            )
            niter = 0
            active = True
            seed = True
            while active and niter < iters:
                enc = self._choose_enc(sf.nnz, W)
                stats["frontier_nnz"].append(sf.nnz)
                obs.observe("serve.shard.frontier_nnz", sf.nnz,
                            kind=kind)
                base = {"kind": kind, "width": W, "epoch": epoch,
                        "level": niter, "enc": enc, "seed": seed}
                if enc == "sparse":
                    base["xs"] = sf
                else:
                    base["x"] = sf.to_dense(np.int32(-1))
                res = self._fan_hop(kind, lambda i: base, enc=enc,
                                    stats=stats)
                seed = False
                if enc == "sparse":
                    sf = SparseFrontier(
                        nc, W,
                        np.concatenate([r["xs"].rows for r in res]),
                        np.concatenate([r["xs"].lanes for r in res]),
                    )
                else:
                    # dense replies are slabs in slice order — their
                    # concatenation index IS the global row id, and a
                    # discovered entry's value is its own row
                    x = np.concatenate([r["x"] for r in res], axis=0)
                    rr, ll = np.nonzero(x >= 0)
                    sf = SparseFrontier(nc, W, rr, ll)
                active = any(r["any"] for r in res)
                niter += 1
            if niter == 0:
                # degenerate cap (max_iters=0): no hop ran, so no
                # resident state exists to collect — seed-only result
                parents = np.full((n, W), -1, np.int32)
                levels = np.full((n, W), -1, np.int32)
                parents[sources[valid], lanes[valid]] = sources[valid]
                levels[sources[valid], lanes[valid]] = 0
                return {"parents": parents, "levels": levels,
                        "batch_niter": 0}
            cres = self._fan_hop(
                kind, lambda i: {"kind": kind, "epoch": epoch},
                op="collect", stats=stats,
            )
            return {
                "parents": np.concatenate(
                    [r["parents"] for r in cres], axis=0
                ),
                "levels": np.concatenate(
                    [r["levels"] for r in cres], axis=0
                ),
                "batch_niter": int(niter),
            }
        if kind == "sssp":
            # the router keeps a host mirror of d in EVERY encoding:
            # triples fold in exactly (slabs are row-disjoint, min is
            # monotone) and the mirror is what a dense-fallback hop
            # broadcasts mid-loop
            d = np.full((nc, W), np.inf, np.float32)
            d[sources[valid], lanes[valid]] = 0.0
            sf = SparseFrontier(
                nc, W, sources[valid], lanes[valid].astype(np.uint8),
                np.zeros(int(valid.sum()), np.float32),
            )
            niter = 0
            changed = True
            seed = True
            while changed and niter < n:
                enc = self._choose_enc(sf.nnz, W)
                stats["frontier_nnz"].append(sf.nnz)
                obs.observe("serve.shard.frontier_nnz", sf.nnz,
                            kind=kind)
                base = {"kind": kind, "width": W, "epoch": epoch,
                        "enc": enc, "seed": seed}
                if enc == "sparse":
                    base["ds"] = sf
                else:
                    base["d"] = d
                res = self._fan_hop(kind, lambda i: base, enc=enc,
                                    stats=stats)
                seed = False
                rows_l, lanes_l, vals_l = [], [], []
                for (r0, r1), r in zip(bounds, res):
                    if "ds" in r:
                        s = r["ds"]
                        d[s.rows, s.lanes.astype(np.int64)] = s.vals
                        rows_l.append(s.rows)
                        lanes_l.append(s.lanes)
                        vals_l.append(s.vals)
                    else:
                        nb = r["d"]
                        chg = nb < d[r0:r1]
                        rr, ll = np.nonzero(chg)
                        rows_l.append((rr + r0).astype(np.int32))
                        lanes_l.append(ll.astype(np.uint8))
                        vals_l.append(nb[rr, ll])
                        d[r0:r1] = nb
                sf = SparseFrontier(
                    nc, W, np.concatenate(rows_l),
                    np.concatenate(lanes_l), np.concatenate(vals_l),
                )
                changed = any(r["any"] for r in res)
                niter += 1
            if niter == 0:
                return {"dist": d, "batch_niter": 0}
            cres = self._fan_hop(
                kind, lambda i: {"kind": kind, "epoch": epoch},
                op="collect", stats=stats,
            )
            dist = np.concatenate([r["d"] for r in cres], axis=0)
            return {"dist": dist, "batch_niter": int(niter)}
        if kind == "propagate":
            q = np.zeros((nc, W), np.float32)
            q[sources[valid], lanes[valid]] = 1.0
            seed = True
            for _ in range(max(self.propagate_hops, 0)):
                base = {"kind": kind, "width": W, "epoch": epoch,
                        "seed": seed, "enc": "dense"}
                base.update(self._pack_q_payload(q))
                res = self._fan_hop(kind, lambda i: base, enc="dense",
                                    stats=stats)
                seed = False
                q = np.concatenate([_unpack_q(r) for r in res],
                                   axis=0)
            # the last hop's q slab is RESIDENT (exact f32) on each
            # slice — the final fan ships no state, except the
            # hops==0 edge where the seed rides the final payload
            fin = {"kind": kind, "width": W, "epoch": epoch,
                   "final": True, "seed": seed}
            if seed:
                fin.update(self._pack_q_payload(q))
            res = self._fan_hop(kind, lambda i: fin, enc="final",
                                stats=stats)
            # fixed slice-order summation: the float partials reduce
            # deterministically (run-to-run stable; vs the unsharded
            # single-dot program it is allclose, not bit-exact)
            feats = res[0]["partial"].astype(np.float32)
            for r in res[1:]:
                feats = feats + r["partial"]
            return {"features": feats[: self.feat_dim]}
        raise ValueError(f"unsupported sharded kind {kind!r}")

    # -- the write lane (two-phase coordinated) ---------------------------

    def apply_delta(self, batch, **kw) -> ShardedGraphVersion:
        """Two-phase durable write (module docstring).  Returns the
        NEW ShardedGraphVersion (the caller — ``Server._merge_once`` —
        stamps and swaps it, the GraphEngine contract)."""
        rows = np.asarray(batch.rows, np.int64)
        cols = np.asarray(batch.cols, np.int64)
        vals = np.asarray(batch.vals, np.float32)
        ops = np.asarray(batch.ops, np.int8)
        first, last = int(batch.first_seq), int(batch.last_seq)
        t0 = time.perf_counter()
        with self._write_lock:
            self._heal(require_all=True)
            # phase 1: the batch becomes durable on EVERY slice before
            # any slice applies it (acknowledged == durable, the
            # round-16 contract, now N logs wide)
            payload = {
                "first_seq": first, "rows": rows, "cols": cols,
                "vals": vals, "ops": ops,
            }
            appended, append_exc = [], None
            futs = []
            for i, sl in enumerate(self.slices):
                try:
                    futs.append((i, sl, sl.rpc(
                        "wal_begin", payload,
                        timeout_s=self.ipc_timeout_s,
                    )))
                except Exception as e:
                    append_exc = append_exc or e
            for i, sl, f in futs:
                try:
                    f.result(timeout=self.ipc_timeout_s + 5)
                    appended.append(sl)
                except Exception as e:
                    self._mark_dead(i, e)
                    append_exc = append_exc or e
            if append_exc is not None or len(appended) != len(
                self.slices
            ):
                # the write was NOT acknowledged: tombstone the logs
                # that did append so recovery cannot resurrect it
                for sl in appended:
                    try:
                        sl.call("wal_abort", {
                            "first_seq": first, "last_seq": last,
                        }, timeout_s=self.ipc_timeout_s)
                    except Exception:
                        pass
                obs.count("serve.shard.write_aborts")
                raise RuntimeError(
                    f"sharded append failed on a slice: {append_exc}"
                )
            # phase 2: apply everywhere (idempotent slice-side).  The
            # exec lock serializes the data flip against in-flight
            # hop loops — a batch never sees two generations.
            commit = {
                "first_seq": first, "last_seq": last, "rows": rows,
                "cols": cols, "vals": vals, "ops": ops,
            }
            with self._exec_lock:
                results = self._commit_all(commit)
            obs.count("serve.shard.writes")
            frontier = [r["wal_seq"] for r in results]
            nnz = sum(r["nnz"] for r in results)
            bytes_ = self._device_bytes_per_slice()
        dt = time.perf_counter() - t0
        v = ShardedGraphVersion(
            nrows=self.spec.nrows, ncols=self.spec.ncols, nnz=nnz,
            bounds=self.spec.bounds, frontier=frontier,
            device_bytes=bytes_,
            merge_stats={"mode": "sharded", "latency_s": dt},
        )
        obs.gauge(
            "serve.shard.frontier_lag",
            max(frontier) - min(frontier) if frontier else 0,
        )
        return v

    def _commit_all(self, commit: dict) -> list:
        results: list = [None] * len(self.slices)
        dead = []
        futs = []
        for i, sl in enumerate(self.slices):
            try:
                futs.append((i, sl.rpc(
                    "wal_commit", commit,
                    timeout_s=self.ipc_timeout_s,
                )))
            except Exception as e:
                self._mark_dead(i, e)
                dead.append(i)
        for i, f in futs:
            try:
                results[i] = f.result(timeout=self.ipc_timeout_s + 5)
            except Exception as e:
                self._mark_dead(i, e)
                dead.append(i)
        if dead:
            # the batch IS durable everywhere (phase 1 succeeded): a
            # dead slice recovers it from its own WAL during the heal,
            # and the re-sent commit is a frontier no-op
            self._heal(require_all=True)
            for i in dead:
                results[i] = self.slices[i].call(
                    "wal_commit", commit,
                    timeout_s=self.ipc_timeout_s,
                )
        return results

    # -- supervision / healing --------------------------------------------

    def _mark_dead(self, i: int, exc: Exception) -> None:
        with self._sup_lock:
            if i in self._needs_rebuild:
                return
            self._needs_rebuild.add(i)
            sl = self.slices[i]
            # fold the dying slice's trace delta into the lost base:
            # marks stay monotone across the respawn
            self._trace_lost += max(
                0, self._last_mark.get(i, 0)
                - self._trace_floor.get(i, 0)
            )
            try:
                sl.quarantine(ReplicaDeadError(
                    f"slice {i} failed: {exc}"
                ))
            except Exception:
                pass
        obs.count("serve.shard.slice_deaths", slice=i)

    def supervise_once(self) -> dict:
        """One deterministic supervision tick (the policy.py stance):
        detect dead/hung slices (sticky), respawn from slab
        snapshot + WAL with capped-backoff retry.  The OTHER slices
        are untouched — this is the recover-ONE-slice property."""
        detected, replaced = [], []
        with self._sup_lock:
            for i, sl in enumerate(self.slices):
                if i in self._needs_rebuild:
                    continue
                hung = (
                    self.hb_timeout_s
                    and isinstance(sl, ProcSlice)
                    and sl.heartbeat_age() > self.hb_timeout_s
                )
                if not sl.is_serving() or hung:
                    self._mark_dead(i, ReplicaDeadError(
                        f"slice {i} "
                        + ("hung (heartbeat timeout)" if hung
                           else "not serving")
                    ))
                    detected.append(i)
            now = time.monotonic()
            for i in sorted(self._needs_rebuild):
                if now < self._replace_next.get(i, 0.0):
                    continue
                try:
                    self._respawn(i)
                except Exception:
                    self.respawn_failures += 1
                    obs.count("serve.shard.respawn_failed", slice=i)
                    b = self._replace_backoff.get(i, 0.5)
                    self._replace_next[i] = now + b
                    self._replace_backoff[i] = min(b * 2, 30.0)
                    continue
                self._needs_rebuild.discard(i)
                self._replace_backoff.pop(i, None)
                self._replace_next.pop(i, None)
                self.replacements += 1
                replaced.append(i)
                obs.count("serve.shard.replacements", slice=i)
        return {"detected": detected, "replaced": replaced}

    def _respawn(self, i: int) -> None:
        old = self.slices[i]
        sl = old.respawn()
        self.slices[i] = sl
        # the respawned slice warm-booted: floor its (fresh) counter
        # so its warmup traces never read as serving retraces
        self._floor_traces(i, sl)

    def _heal(self, require_all: bool = False) -> None:
        """Drive supervision until every slice serves again (bounded
        by ``recover_wait_s``)."""
        t0 = time.monotonic()
        while True:
            self.supervise_once()
            with self._sup_lock:
                pending = set(self._needs_rebuild)
            if not pending and all(
                sl.is_serving() for sl in self.slices
            ):
                if t0 != time.monotonic():
                    obs.observe("serve.shard.heal_wait_s",
                                time.monotonic() - t0)
                return
            if time.monotonic() - t0 > self.recover_wait_s:
                if require_all:
                    raise RuntimeError(
                        f"slices {sorted(pending)} did not heal "
                        f"within {self.recover_wait_s}s"
                    )
                return
            time.sleep(0.05)

    def start_supervisor(self, interval_s: float = 0.25) -> None:
        if self._sup_thread is not None:
            return
        self._sup_stop.clear()

        def loop():
            while not self._sup_stop.wait(interval_s):
                try:
                    self.supervise_once()
                except Exception:
                    obs.count("serve.shard.supervisor_errors")

        self._sup_thread = threading.Thread(
            target=loop, name="combblas-shard-supervisor", daemon=True
        )
        self._sup_thread.start()

    def stop_supervisor(self) -> None:
        self._sup_stop.set()
        if self._sup_thread is not None:
            self._sup_thread.join(timeout=5.0)
            self._sup_thread = None

    # -- snapshots / introspection ----------------------------------------

    def checkpoint_now(self, reason: str = "manual") -> dict:
        futs = [
            (i, sl.rpc("checkpoint_now", {"reason": reason},
                       timeout_s=self.ipc_timeout_s * 2))
            for i, sl in enumerate(self.slices)
        ]
        out = {}
        for i, f in futs:
            out[i] = f.result(timeout=self.ipc_timeout_s * 2 + 5)
        self._version.frontier = [
            int(out[i]["wal_seq"]) for i in range(len(self.slices))
        ]
        self._version.wal_seq = min(self._version.frontier)
        self._write_manifest()
        return {
            "frontier": list(self._version.frontier),
            "slices": out, "reason": reason,
        }

    def _poll_slices(self):
        frontier, nnzs, bytes_ = [], [], []
        for sl in self.slices:
            s = sl.call("stats", timeout_s=self.ipc_timeout_s)
            frontier.append(int(s["wal_seq"]))
            nnzs.append(int(s["nnz"]))
            bytes_.append(int(s["device_bytes"]))
        return frontier, nnzs, bytes_

    def _device_bytes_per_slice(self) -> list:
        out = []
        for sl in self.slices:
            try:
                out.append(int(sl.call(
                    "device_bytes", timeout_s=self.ipc_timeout_s
                )["bytes"]))
            except Exception:
                out.append(0)
        return out

    def to_host_coo(self):
        """The global edge list, re-assembled and key-sorted — equal
        (np.array_equal) to what an unsharded ``keep_coo=True`` build
        of the same acknowledged writes retains (the recovery gate's
        comparison surface)."""
        parts = [
            sl.call("to_host_coo", timeout_s=self.ipc_timeout_s)
            for sl in self.slices
        ]
        rows = np.concatenate([p["rows"] for p in parts])
        cols = np.concatenate([p["cols"] for p in parts])
        ws = [p["weights"] for p in parts]
        weights = (
            None if any(w is None for w in ws)
            else np.concatenate(ws)
        )
        order = np.argsort(
            rows * np.int64(self.spec.ncols) + cols, kind="stable"
        )
        return (
            rows[order], cols[order],
            None if weights is None else weights[order],
        )

    def stats(self) -> dict:
        per_slice = {}
        plans: dict = {}
        hits = misses = swaps = 0
        for i, sl in enumerate(self.slices):
            try:
                s = sl.call("stats", timeout_s=self.ipc_timeout_s)
            except Exception as e:
                per_slice[i] = {"error": repr(e)}
                continue
            per_slice[i] = s
            hits += s.get("plan_hits", 0)
            misses += s.get("plan_misses", 0)
            swaps += s.get("swaps", 0)
            for kw, rec in (s.get("plans") or {}).items():
                agg = plans.setdefault(
                    kw, {"traces": 0, "executions": 0}
                )
                agg["traces"] += rec["traces"]
                agg["executions"] += rec["executions"]
        return {
            "plans": plans,
            "plan_hits": hits,
            "plan_misses": misses,
            "nrows": self.nrows,
            "kinds": list(self._kinds),
            "graph_version": self._version.vid,
            "graph_nnz": self._version.nnz,
            "swaps": self.swaps,
            "freshness": {
                "refresh_modes": {}, "repair_ratio": None,
                "versions_behind": 0,
            },
            "shard": {
                "nslices": self.spec.nslices,
                "bounds": [list(b) for b in self.spec.bounds],
                "frontier_mode": self.frontier_mode,
                "density_threshold": self.density_threshold,
                "wire": self.wire,
                "last_exec": dict(self.last_exec_stats),
                "frontier": list(self._version.frontier),
                "device_bytes_per_slice":
                    list(self._version.device_bytes_per_slice),
                "replacements": self.replacements,
                "respawn_failures": self.respawn_failures,
                "needs_rebuild": sorted(self._needs_rebuild),
                "slices": per_slice,
            },
        }

    def close(self) -> None:
        self.stop_supervisor()
        for sl in self.slices:
            try:
                sl.close()
            except Exception:
                pass
        obs.gauge("serve.shard.slices", 0)


# --------------------------------------------------------------------------
# local-mode factories (kept top-level so recovery closures stay small)
# --------------------------------------------------------------------------


def _local_factory(i, r0, r1, n, nc, lrows, lcols, lw, kinds, *,
                   features, headroom, home, fsync, max_iters,
                   propagate_hops, checkpoint_every,
                   checkpoint_retain, warmup, warmup_widths):
    from ..parallel.grid import Grid

    def factory(recover: bool) -> SliceRuntime:
        grid = Grid.make(1, 1)
        if recover:
            rt = SliceRuntime.recover(
                grid, i, home, kinds, fsync=fsync,
                max_iters=max_iters, propagate_hops=propagate_hops,
                checkpoint_every=checkpoint_every,
                checkpoint_retain=checkpoint_retain,
            )
            if warmup:
                rt.warmup(widths=warmup_widths)
            return rt
        return SliceRuntime.build(
            grid, i, r0, r1, n, nc, lrows, lcols, lw, kinds,
            features=features, headroom=headroom, home=home,
            fsync=fsync, max_iters=max_iters,
            propagate_hops=propagate_hops,
            checkpoint_every=checkpoint_every,
            checkpoint_retain=checkpoint_retain,
        )

    return factory


def _local_recover_factory(i, home, kinds, *, max_iters,
                           propagate_hops):
    from ..parallel.grid import Grid

    def factory(recover: bool) -> SliceRuntime:
        return SliceRuntime.recover(
            Grid.make(1, 1), i, home, kinds, max_iters=max_iters,
            propagate_hops=propagate_hops,
        )

    return factory
