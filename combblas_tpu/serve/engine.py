"""GraphEngine — a loaded graph plus a shape-bucketed warm plan cache.

The batch kernels make TPUs pay off only when (a) requests share one
launch and (b) the launched executable already exists. The engine owns
both halves for one graph:

* the loaded matrices and derived artifacts: the structural
  ``EllParMat`` (BFS/BC/PageRank-structure), its weighted twin (SSSP),
  the column-normalized PageRank transition matrix + dangling vector,
  the transpose (BC on directed graphs) and the row/column degree
  vectors (``coldeg``) — built host-side once at load, uploaded once;
  the CSC companion tiers (``csc_companion()``, the future
  sparse-regime hook) build lazily on first use;
* a **plan cache** keyed by (query kind, lane width): each plan is one
  jitted program whose trace increments both a host-side counter and
  the ``trace.serve`` obs counter (trace-time side effects count
  RETRACES, not executions — the zero-retrace acceptance gate), so
  ``warmup()`` over the configured lane buckets guarantees steady-state
  requests never trace or compile.

The loaded state lives on a ``GraphVersion`` and plans take their
matrices as CALL-TIME jit arguments, so ``swap()`` can atomically
replace the whole graph under the execution lock while the plan cache
survives (zero retraces for same-shape versions) — the hot-swap half
of dynamic-graph serving; ``build_version()`` constructs the next
generation off-lock (double-buffered).

The engine is synchronous and thread-safe: plan building, ``warmup``
and ``execute`` serialize on one internal lock (one execution stream —
a caller-thread ``warmup()`` cannot race the api worker's batches);
results come back as HOST numpy arrays, so ``execute`` is the
device→host sync point.
On readback-poisoned chips run the engine in a dedicated serving
process, exactly like bench children (bench.py's axon D2H note).
"""

from __future__ import annotations

import dataclasses
import threading
import time

import numpy as np

from .. import obs
from ..models import PAD_ROOT

#: Query kinds the engine can build plans for.  ``"propagate"`` (round
#: 12) is the graph-ML lane: lane w of a batch answers "the k-hop
#: propagated feature row of vertex w" via the batched SpMM kernels
#: (models/propagate.py) — it needs a feature table
#: (``from_coo(features=...)``).
KINDS = ("bfs", "sssp", "pagerank", "bc", "propagate")


@dataclasses.dataclass
class _Plan:
    """One warm executable: (kind, width) -> jitted program + metadata."""

    kind: str
    width: int
    fn: object  # jitted callable
    traces: int = 0  # incremented at TRACE time (retrace counter)
    executions: int = 0


@dataclasses.dataclass
class GraphVersion:
    """One immutable generation of loaded graph state — everything a
    plan's operands come from, bundled so the engine can swap it
    ATOMICALLY (one reference flip under the execution lock) while the
    plan cache survives.

    Plans are jitted over these matrices as ARGUMENTS (not closed-over
    constants), so a swap to a version with identical operand shapes
    (same nrows/ncols and ELL tile widths) re-uses every compiled
    executable: zero retraces. A version with different shapes serves
    correctly but pays one retrace per (kind, width) on first use —
    visible in ``trace.serve`` / ``retraces_since``.
    """

    nrows: int
    ncols: int
    nnz: int
    E: object                      # structural EllParMat
    deg: object                    # host [nrows] in-degree
    outdeg: object                 # host [ncols] out-degree
    E_weighted: object = None      # None => unit weights (falls back to E)
    P_ell: object = None           # pagerank transition matrix
    dangling: object = None        # pagerank dangling DistVec
    ET: object = None              # None => symmetric (E is its own T)
    csc: object = None             # lazy CSC companion cache
    coldeg: object = None          # lazy col-degree DistVec cache
    host_coo: tuple | None = None  # retained iff keep_coo=True
    host_weights: object = None    # deduped weights (the mutation lane)
    X: object = None               # propagate feature table (row-aligned
    #                                DistMultiVec, pow2-padded F)
    feat_dim: int = 0              # TRUE feature width (pad stripped)
    invdeg: object = None          # lazy col-aligned 1/deg DistVec (the
    #                                normalized-propagation twin; reset
    #                                on merge — degrees changed)
    headroom: float | None = None  # bucket-slot slack this version's
    #                                ELL builds reserved (merge state
    #                                must re-bucket with the same value)
    dyn: object = None             # dynamic.merge.MergeState (host
    #                                bucket structure for apply_delta)
    delta_from: tuple | None = None  # (parent vid, inserted keys,
    #                                removed keys) — refresh lineage
    vid: int = 0                   # assigned when installed/swapped in
    wal_seq: int = -1              # highest WAL sequence number folded
    #                                into this version (-1 = none) —
    #                                stamped into snapshot meta so
    #                                recovery replays exactly the
    #                                unapplied log suffix (round 16)

    def device_bytes(self) -> int:
        """Resident DEVICE bytes of this version: every uploaded array
        a plan's operands can come from (the ELL matrices and their
        twins, the feature table, the pagerank/dangling and lazy
        degree vectors, the CSC companion).  The multi-tenant pool's
        byte-accounted LRU evicts against this number
        (``serve.pool.resident_bytes``); host-side state (COO, degree
        tables, merge state) is deliberately NOT counted — eviction
        frees the device, the host retains the rebuild inputs."""
        total = 0
        for M in (self.E, self.E_weighted, self.P_ell, self.ET):
            if M is not None:
                total += sum(
                    int(a.nbytes) for b in M.buckets for a in b
                )
        for vec in (self.dangling, self.coldeg, self.invdeg, self.X):
            blocks = getattr(vec, "blocks", None)
            if blocks is not None:
                total += int(blocks.nbytes)
        if self.csc is not None:  # (indptr, rowidx) device pair
            total += sum(int(a.nbytes) for a in self.csc)
        return total


def _build_version(grid, rows, cols, nrows: int, ncols: int,
                   weights, kinds: tuple[str, ...], symmetric: bool,
                   keep_coo: bool, features=None,
                   headroom: float | None = None) -> GraphVersion:
    """Host-side construction of every artifact ``kinds`` need (the
    body of the old ``from_coo``): dedup the COO, build the structural
    / weighted / normalized / transposed matrices and the degree
    tables. Runs WITHOUT any engine lock — this is the double-buffered
    half of hot-swap: build the next generation while the current one
    keeps serving."""
    from ..parallel.ellmat import EllParMat
    from ..parallel.vec import DistVec

    from ..tuner import config as tuner_config

    # resolve the env default NOW and store the concrete value: the
    # merge state must re-bucket with the slack the build ACTUALLY
    # used, not whatever COMBBLAS_DYNAMIC_HEADROOM says at merge time
    # (a changed env between build and merge would silently desync
    # orientation shapes from the retained device arrays)
    headroom = tuner_config.dynamic_headroom(headroom)
    rows = np.asarray(rows)
    cols = np.asarray(cols)
    n = int(nrows)
    ncols = int(ncols)
    key = rows.astype(np.int64) * np.int64(ncols) + cols
    uniq, inv = np.unique(key, return_inverse=True)
    if weights is not None:
        w = np.full(len(uniq), np.inf, np.float32)
        np.minimum.at(w, inv, np.asarray(weights, np.float32))
        weights = w
    rows = (uniq // ncols).astype(rows.dtype)
    cols = (uniq % ncols).astype(cols.dtype)
    if "propagate" in kinds and ncols != n:
        # k-hop propagation chains ONE square operator; an explicit
        # kinds=("propagate",) on a rectangular graph would otherwise
        # die mid-trace at the second hop with a bare shape assert
        raise ValueError(
            f"'propagate' needs a square graph (nrows={n}, "
            f"ncols={ncols}): A^k is undefined on rectangles"
        )
    if ("bc" in kinds or "propagate" in kinds) and symmetric:
        # VERIFY the symmetry claim instead of trusting it: under
        # symmetric=True bc AND propagate reuse E as its own transpose,
        # and a forgotten symmetric=False would make every served score
        # silently wrong (bc's backward sweep would walk out-edges;
        # propagate's indicator hops would aggregate the wrong side)
        tkey = np.sort(
            cols.astype(np.int64) * np.int64(ncols) + rows
        )
        if ncols != n or not np.array_equal(uniq, tkey):
            raise ValueError(
                "symmetric=True but the COO is not structurally "
                "symmetric; pass symmetric=False (builds the "
                "transpose for bc) or symmetrize the graph"
            )
    with obs.span("serve.load", nrows=n, nnz=int(len(rows))):
        ones = np.ones(len(rows), np.float32)
        E = EllParMat.from_host_coo(grid, rows, cols, ones, n, ncols,
                                    headroom=headroom)
        E_weighted = (
            EllParMat.from_host_coo(
                grid, rows, cols,
                np.asarray(weights, np.float32), n, ncols,
                headroom=headroom,
            )
            if weights is not None else None
        )
        # degree artifacts: rowdeg = in-edges per row; outdeg feeds
        # the pagerank normalization and the lazy coldeg_vec()
        # (device upload deferred until a plan consumes it)
        deg = np.bincount(rows, minlength=n).astype(np.int32)
        outdeg = np.bincount(cols, minlength=ncols).astype(np.int64)
        P_ell = dangling = None
        if "pagerank" in kinds:
            # column-stochastic normalization, host-side (the
            # reference's DimApply, PageRank.cpp:97-126)
            pvals = (
                1.0 / np.maximum(outdeg[cols], 1)
            ).astype(np.float32)
            P_ell = EllParMat.from_host_coo(
                grid, rows, cols, pvals, n, ncols, headroom=headroom
            )
            dangling = DistVec.from_global(
                grid, (outdeg == 0).astype(np.float32), align="col"
            )
        ET = None
        if ("bc" in kinds or "propagate" in kinds) and not symmetric:
            ET = EllParMat.from_host_coo(grid, cols, rows, ones,
                                         ncols, n, headroom=headroom)
        X = None
        feat_dim = 0
        # like every other artifact here, the feature table is built
        # only when a served kind needs it: a features= arg whose
        # 'propagate' was excluded (rectangular default kinds,
        # explicit kinds=) must neither pay the [n, Fp] upload nor be
        # validated against a contract nothing will serve
        if features is not None and "propagate" in kinds:
            from ..parallel.spmm import pad_features
            from ..parallel.vec import DistMultiVec

            features = np.asarray(features, np.float32)
            if features.shape[0] != ncols:
                raise ValueError(
                    f"features rows {features.shape[0]} != graph "
                    f"column space {ncols} (one feature row per "
                    "vertex the hops aggregate from)"
                )
            feat_dim = int(features.shape[1])
            # pow2 pad: propagate plans compile per padded F, so two
            # versions inside one feature-width bucket share programs
            X = DistMultiVec.from_global(
                grid, pad_features(features), align="row"
            )
            obs.gauge("serve.propagate.feature_dim", feat_dim)
    return GraphVersion(
        nrows=n, ncols=ncols, nnz=int(len(rows)), E=E, deg=deg,
        outdeg=outdeg, E_weighted=E_weighted, P_ell=P_ell,
        dangling=dangling, ET=ET,
        host_coo=(rows, cols, ncols) if keep_coo else None,
        # the deduped (min-combined) weights ride along for the
        # mutation lane's merge-state bootstrap
        host_weights=weights if keep_coo else None,
        X=X, feat_dim=feat_dim, headroom=headroom,
    )


class GraphEngine:
    """One graph, loaded and query-ready. See module docstring.

    Build with ``GraphEngine.from_coo`` (host COO in the usual gather
    orientation: entry (i, j) means edge j -> i; symmetrize for
    undirected graphs). ``serve()`` wraps the engine in the batched,
    backpressured server (``combblas_tpu.serve.api.Server``).
    """

    def __init__(self, grid, E=None, *, nrows: int | None = None,
                 deg: np.ndarray | None = None,
                 E_weighted=None, P_ell=None, dangling=None, ET=None,
                 csc=None, coldeg=None, kinds: tuple[str, ...] | None = None,
                 pagerank_opts: tuple = (0.85, 1e-6, 100),
                 propagate_opts: tuple = (2, False),
                 max_iters: int | None = None,
                 version: GraphVersion | None = None):
        self.grid = grid
        if version is None:
            if E is None or nrows is None or deg is None:
                raise ValueError(
                    "GraphEngine needs either version= or E/nrows/deg"
                )
            version = GraphVersion(
                nrows=int(nrows),
                # read the real column count off E (a rectangular
                # engine's dedup keys and swap validation depend on it)
                ncols=int(getattr(E, "ncols", nrows)),
                nnz=-1,
                E=E, deg=np.asarray(deg),
                outdeg=None,
                E_weighted=E_weighted, P_ell=P_ell, dangling=dangling,
                ET=ET, csc=csc, coldeg=coldeg,
            )
        version.vid = 1
        self._version = version
        self.nrows = int(version.nrows)
        self.swaps = 0
        weighted_given = version.E_weighted is not None
        # kinds this engine was built to serve: only these get plans —
        # a kind whose artifacts were never built must be rejected at
        # the front door, not served with a silently-wrong stand-in
        # (no P_ell -> no pagerank; no weighted matrix -> no sssp, hop
        # counts are not distances; explicit kinds= opts back in)
        if kinds is None:
            kinds = tuple(
                k for k in KINDS
                if (k != "pagerank" or version.P_ell is not None)
                and (k != "sssp" or weighted_given)
                and (k != "propagate" or version.X is not None)
            )
        self._kinds = tuple(kinds)
        self.pagerank_opts = pagerank_opts
        self.propagate_opts = propagate_opts
        self.max_iters = max_iters
        # the SpMM backend resolves ONCE per engine through the tuner
        # chain (op="spmm"; lazily on first propagate plan build) and
        # stays static inside every compiled propagate plan
        self._spmm_backend: str | None = None
        self._plans: dict[tuple[str, int], _Plan] = {}
        # whole-graph analytics cache for refresh(): (kind, root) ->
        # {vid, result, niter} — the warm-restart recompute's memory
        self._analytics: dict = {}
        # refresh-mode history (cached/warm/cold counts): the
        # freshness surface (repair-vs-cold ratio) stats() reports and
        # dynamic.refresh emits as gauges
        self._refresh_modes: dict[str, int] = {}
        # ONE execution stream: plan building, warmup, and execute all
        # serialize here, so a caller-thread warmup() cannot race the
        # api worker's pump() on the plan cache (or the device)
        self._exec_lock = threading.RLock()
        # plan-cache DICT mutations/snapshots only — stats() must be
        # pollable during a long batch, so it must not touch _exec_lock
        self._plans_lock = threading.Lock()
        self.plan_hits = 0
        self.plan_misses = 0

    # -- version delegation ------------------------------------------------
    # The loaded matrices live on the CURRENT GraphVersion; these
    # properties keep the pre-versioning attribute surface (engine.E,
    # engine.ET, ...) working while making every read swap-aware.

    @property
    def version(self) -> GraphVersion:
        return self._version

    @property
    def version_id(self) -> int:
        return self._version.vid

    @property
    def E(self):
        return self._version.E

    @property
    def deg(self):
        return self._version.deg

    @property
    def E_weighted(self):
        v = self._version
        return v.E_weighted if v.E_weighted is not None else v.E

    @property
    def P_ell(self):
        return self._version.P_ell

    @property
    def dangling(self):
        return self._version.dangling

    @property
    def ET(self):
        v = self._version
        return v.ET if v.ET is not None else v.E  # symmetric default

    @property
    def csc(self):
        return self._version.csc

    @csc.setter
    def csc(self, value):
        self._version.csc = value

    @property
    def coldeg(self):
        return self._version.coldeg

    @coldeg.setter
    def coldeg(self, value):
        self._version.coldeg = value

    @property
    def _outdeg(self):
        return self._version.outdeg

    @property
    def _host_coo(self):
        return self._version.host_coo

    @_host_coo.setter
    def _host_coo(self, value):
        self._version.host_coo = value

    # -- construction ------------------------------------------------------

    @staticmethod
    def from_coo(grid, rows, cols, nrows: int, ncols: int | None = None,
                 weights=None, kinds: tuple[str, ...] | None = None,
                 pagerank_alpha: float = 0.85, pagerank_tol: float = 1e-6,
                 pagerank_max_iters: int = 100,
                 max_iters: int | None = None,
                 symmetric: bool = True,
                 keep_coo: bool = False,
                 features=None,
                 propagate_hops: int = 2,
                 propagate_normalize: bool = False,
                 headroom: float | None = None) -> "GraphEngine":
        """Load a graph from host COO and build every derived artifact
        the requested ``kinds`` need (one host pass + one upload each —
        the kernel-1 role, amortized over the engine's whole lifetime).

        ``kinds`` defaults to every kind whose inputs were actually
        given: without ``weights``, 'sssp' is EXCLUDED (serving hop
        counts as "distances" would be a silent stand-in) — pass
        ``kinds`` naming it explicitly to serve unit-weight SSSP on a
        genuinely unweighted graph.

        The COO is DEDUPLICATED here (generators like
        ``rmat_symmetric_coo`` emit repeats, and a duplicate edge would
        silently act as weight-2 in BC's path counting); duplicate
        weighted edges keep the MINIMUM weight (the shortest-path
        natural combine, matching the reference's dedup-at-construction
        convention, ``SpParMat.from_global_coo dedup_sr=``).

        ``features`` ([n, F] host array) opts into the ``"propagate"``
        kind: lane w of a propagate batch returns the k-hop propagated
        feature row of vertex w (``propagate_hops`` hops;
        ``propagate_normalize=True`` serves the degree-normalized
        smoothing ``(D⁻¹A)ᵏX``).  ``headroom`` reserves a slack
        fraction of padding slots per ELL bucket class at build
        (``COMBBLAS_DYNAMIC_HEADROOM``) so the dynamic mutation lane
        re-buckets growing rows instead of spilling to a rebuild.
        """
        ncols = nrows if ncols is None else int(ncols)
        n = int(nrows)
        if kinds is None:
            kinds = tuple(
                k for k in KINDS
                if (k != "sssp" or weights is not None)
                and (k != "bc" or ncols == n)  # bc needs a square graph
                # propagate chains hops through one square operator —
                # a rectangular graph has no A^k to serve
                and (k != "propagate"
                     or (features is not None and ncols == n))
            )
        version = _build_version(
            grid, rows, cols, n, ncols, weights, tuple(kinds),
            symmetric, keep_coo, features=features, headroom=headroom,
        )
        return GraphEngine(
            grid, version=version, kinds=tuple(kinds),
            pagerank_opts=(pagerank_alpha, pagerank_tol,
                           pagerank_max_iters),
            propagate_opts=(int(propagate_hops),
                            bool(propagate_normalize)),
            max_iters=max_iters,
        )

    # -- graph versions / hot-swap -----------------------------------------

    def build_version(self, rows, cols, weights=None,
                      ncols: int | None = None, symmetric: bool = True,
                      keep_coo: bool = False,
                      features=None) -> GraphVersion:
        """Build the NEXT graph generation for this engine — same
        nrows, same kinds — entirely outside the execution lock (the
        double-buffered half of hot-swap: current version keeps
        serving while this one is constructed host-side + uploaded).
        Hand the result to ``swap()`` (or ``Server.swap_graph``)."""
        t0 = time.perf_counter()
        v = _build_version(
            self.grid, rows, cols, self.nrows,
            # default to the CURRENT version's ncols (not nrows): a
            # rectangular engine's dedup key and index split are
            # ncols-based, and a silently-wrong ncols would merge
            # distinct edges
            self._version.ncols if ncols is None else int(ncols),
            weights, self._kinds, symmetric, keep_coo,
            features=features,
            # bucket shapes must round-trip the swap: reuse the
            # engine's configured headroom
            headroom=self._version.headroom,
        )
        if v.X is None and self._version.X is not None:
            # features are edge-independent: a version rebuilt without
            # an explicit new table KEEPS the served one (same device
            # arrays — no re-upload, no retrace)
            v.X = self._version.X
            v.feat_dim = self._version.feat_dim
        obs.observe("serve.swap.build_s", time.perf_counter() - t0)
        return v

    def apply_delta(self, batch, **kw) -> GraphVersion:
        """Build the NEXT version by merging a delta batch into the
        CURRENT one (``combblas_tpu.dynamic.merge.apply_delta`` — per
        tile, slot-capacity-aware, spill-to-rebuild; see
        docs/dynamic.md).  Like ``build_version`` this runs entirely
        OUTSIDE the execution lock and the current version keeps
        serving; hand the result to ``swap()`` / ``Server.swap_graph``
        — an incremental merge preserves every operand shape, so the
        swap keeps the zero-retrace guarantee.  Requires the host edge
        list (``from_coo(..., keep_coo=True)``)."""
        from ..dynamic import merge as dyn_merge

        t0 = time.perf_counter()
        v = dyn_merge.apply_delta(
            self._version, batch, kinds=self._kinds, **kw
        )
        obs.observe("serve.swap.build_s", time.perf_counter() - t0)
        return v

    def refresh(self, kind: str, root: int | None = None,
                force_cold: bool = False) -> dict:
        """Whole-graph analytic with warm-restart recompute
        (``dynamic.refresh``): BFS levels from ``root``, CC labels, or
        the global PageRank vector — repaired from the engine's cached
        previous result when the current version's delta lineage allows
        it (insert-only for bfs/cc; always for pagerank), recomputed
        cold otherwise.  Returns ``{"result", "niter", "mode"
        (cached/warm/cold), "vid", ...}`` with host numpy results.
        Serialized on the execution lock like every device access."""
        from ..dynamic.refresh import refresh_analytic

        with self._exec_lock:
            return refresh_analytic(
                self, kind, root=root, force_cold=force_cold
            )

    def swap(self, version: GraphVersion) -> float:
        """Atomically install ``version`` as the current graph. Blocks
        on the execution lock, so the in-flight batch (if any) finishes
        on the OLD version; every later execute reads the new one. The
        plan cache is untouched — plans take the matrices as call-time
        arguments, so same-shape versions re-use every compiled
        executable (zero retraces; a different-shape version retraces
        once per plan, visibly). Returns the swap latency in seconds
        (lock wait + pointer flip), also an obs histogram
        (``serve.swap.latency_s``)."""
        if not isinstance(version, GraphVersion):
            raise TypeError(
                f"swap() takes a GraphVersion (see build_version), "
                f"got {type(version).__name__}"
            )
        if int(version.nrows) != self.nrows:
            # results are [nrows, W]: changing nrows breaks every
            # queued caller's contract — that is a new engine, not a
            # version swap
            raise ValueError(
                f"version nrows={version.nrows} != engine nrows="
                f"{self.nrows}; hot-swap preserves the result shape"
            )
        if int(version.ncols) != int(self._version.ncols):
            raise ValueError(
                f"version ncols={version.ncols} != engine ncols="
                f"{self._version.ncols}; a different column space is "
                "a new engine, not a version swap"
            )
        if "pagerank" in self._kinds and version.P_ell is None:
            raise ValueError(
                "engine serves 'pagerank' but the new version has no "
                "P_ell; build it via engine.build_version(...)"
            )
        if "propagate" in self._kinds and version.X is None:
            raise ValueError(
                "engine serves 'propagate' but the new version has no "
                "feature table; pass features= to build_version (or "
                "reuse the current one via engine.build_version)"
            )
        if (
            "sssp" in self._kinds
            and self._version.E_weighted is not None
            and version.E_weighted is None
        ):
            # a weighted engine must stay weighted: the E_weighted
            # property would silently fall back to the structural E
            # and serve hop counts as distances (an engine built
            # unit-weight by explicit kinds= opt-in stays consistent)
            raise ValueError(
                "engine serves weighted 'sssp' but the new version "
                "has no weights; pass weights= to build_version"
            )
        t0 = time.perf_counter()
        with self._exec_lock:
            version.vid = self._version.vid + 1
            self._version = version
            self.swaps += 1
        dt = time.perf_counter() - t0
        obs.observe("serve.swap.latency_s", dt)
        obs.gauge("serve.graph.version", version.vid)
        obs.count("serve.swap.count")
        return dt

    def coldeg_vec(self):
        """Col-aligned out-degree DistVec (the budget input of the
        direction-optimized kernels) — built lazily like
        ``csc_companion``: no current dense plan consumes it, so the
        device upload is deferred to first use and cached."""
        if self.coldeg is None:
            outdeg = getattr(self, "_outdeg", None)
            if outdeg is None:
                raise ValueError(
                    "coldeg_vec needs the degree table: build the "
                    "engine with GraphEngine.from_coo"
                )
            from ..parallel.vec import DistVec

            self.coldeg = DistVec.from_global(
                self.grid, outdeg.astype(np.int32), align="col"
            )
        return self.coldeg

    def csc_companion(self):
        """The CSC companion tiers (``ellmat.build_csc_companion``) —
        the direction-optimization hook for future sparse-regime serve
        plans. Built LAZILY on first use (it is dead weight for the
        dense batch kernels the current plans run) and cached; needs
        the host COO, so it requires ``from_coo(..., keep_coo=True)``
        (opt-in: retaining the edge list costs ~8 bytes/nnz of host RAM
        for the engine's lifetime). The COO is released after the
        build — the companion caches, the edge list does not linger.
        """
        if self.csc is None:
            if self._host_coo is None:
                raise ValueError(
                    "csc_companion needs the host COO: build the "
                    "engine with GraphEngine.from_coo(keep_coo=True)"
                )
            from ..parallel.ellmat import build_csc_companion

            rows, cols, ncols = self._host_coo
            self.csc = build_csc_companion(
                self.grid, rows, cols, self.nrows, ncols
            )
            self._host_coo = None  # companion built: drop the edge list
        return self.csc

    def serve(self, config=None, tenant: str | None = None):
        from .api import Server
        from .scheduler import ServeConfig

        return Server(self, config or ServeConfig(), tenant=tenant)

    # -- plan cache --------------------------------------------------------

    def kinds(self) -> tuple[str, ...]:
        """The kinds this engine was BUILT to serve — a kind outside
        this set is rejected (its artifacts may not exist: e.g. ET for
        BC on a directed graph), never served with a stand-in."""
        return self._kinds

    def plan(self, kind: str, width: int) -> _Plan:
        """The warm executable for (kind, width) — built (a cache MISS,
        which traces and possibly compiles) only on first use; warm it
        via ``warmup()`` so serving never misses."""
        if kind not in self._kinds:
            raise ValueError(
                f"engine was not built for kind {kind!r} "
                f"(kinds={self._kinds})"
            )
        key = (kind, int(width))
        with self._exec_lock:
            with self._plans_lock:
                p = self._plans.get(key)
            if p is not None:
                self.plan_hits += 1
                obs.count("serve.plan_cache.hits", kind=kind, width=width)
                return p
            self.plan_misses += 1
            obs.count("serve.plan_cache.misses", kind=kind, width=width)
            p = self._build_plan(kind, int(width))
            with self._plans_lock:
                self._plans[key] = p
            self._record_lane(kind, int(width))
            return p

    def _record_lane(self, kind: str, width: int) -> None:
        """Remember a traced (kind, width) lane in the persisted plan
        store (round 10): a FRESH process's ``warmup()`` replays the
        recorded lane set, reaching zero-retrace steady state without
        re-discovering which lanes the traffic mix actually uses.
        Best-effort — a store problem must never fail serving."""
        try:
            from ..tuner import store as plan_store

            st = plan_store.get_store()
            if st is not None:
                st.add_serve_lane(
                    plan_store.serve_plan_key(self), kind, width
                )
        except Exception:
            pass

    def _build_plan(self, kind: str, width: int) -> _Plan:
        import jax

        from ..models.bc import _bc_batch_dense_impl
        from ..models.bfs import _bfs_batch_impl
        from ..models.pagerank import _pagerank_batch_impl
        from ..models.sssp import _sssp_batch_impl

        plan = _Plan(kind=kind, width=width, fn=None)

        def trace_mark():
            # runs at TRACE time only: counts (re)traces, not executions
            plan.traces += 1
            obs.count("trace.serve", kind=kind, width=width)

        if kind == "bfs":

            def impl(E, sources):
                trace_mark()
                return _bfs_batch_impl(
                    E, sources, max_iters=self.max_iters,
                )

        elif kind == "sssp":

            def impl(E, sources):
                trace_mark()
                return _sssp_batch_impl(E, sources)

        elif kind == "pagerank":
            if self.P_ell is None:
                raise ValueError(
                    "engine was built without the pagerank artifacts "
                    "(kinds= did not include 'pagerank')"
                )
            alpha, tol, iters = self.pagerank_opts

            def impl(P, dangling, sources):
                trace_mark()
                return _pagerank_batch_impl(
                    P, sources, dangling, alpha=alpha, tol=tol,
                    max_iters=iters,
                )

        elif kind == "bc":

            def impl(E, ET, sources):
                trace_mark()
                return _bc_batch_dense_impl(
                    E, ET, sources, max_depth=self.max_iters,
                    per_lane=True,
                )

        elif kind == "propagate":
            from ..models.propagate import _propagate_batch_impl

            if self._version.X is None:
                raise ValueError(
                    "engine was built without a feature table "
                    "(from_coo(features=...) opts into 'propagate')"
                )
            hops, normalize = self.propagate_opts
            backend = self._resolve_spmm_backend()

            def impl(ET, X, invdeg, sources):
                trace_mark()
                return _propagate_batch_impl(
                    ET, X, invdeg, sources, hops=hops,
                    normalize=normalize, backend=backend,
                )

        else:
            raise ValueError(f"unknown query kind {kind!r}")

        jitted = jax.jit(impl)
        # operands resolved at CALL time from the current GraphVersion
        # (not closed over): this is what lets swap() replace the graph
        # under a surviving plan cache — same-shape operands hit the
        # jit signature cache, different shapes retrace exactly once
        plan.fn = lambda sources: jitted(*self._plan_args(kind), sources)
        return plan

    def _resolve_spmm_backend(self) -> str:
        """The op="spmm" tuner resolution, ONCE per engine (the plan
        store remembers it across processes; the result is a static
        closure constant of every propagate plan).

        Keyed at the WIDEST warmup LANE width, not the feature-table
        width: the plan's hot kernels are the k indicator hops over
        the [n, W] batch block (the table enters once, in a
        backend-independent dense dot), so a measurement cached under
        the whole-graph F-width key would describe a different kernel
        shape — and the two resolutions must not pollute each other's
        store records."""
        if self._spmm_backend is None:
            from ..parallel.spmm import resolve_spmm_backend
            from ..semiring import PLUS_TIMES

            self._spmm_backend = resolve_spmm_backend(
                PLUS_TIMES, self.ET, max(self.DEFAULT_WARMUP_WIDTHS),
            )
        return self._spmm_backend

    def _propagate_invdeg(self):
        """Col-aligned 1/deg DistVec for normalized propagation — lazy
        per version (a merge resets it: degrees changed)."""
        v = self._version
        if v.invdeg is None:
            from ..parallel.vec import DistVec

            v.invdeg = DistVec.from_global(
                self.grid,
                (1.0 / np.maximum(v.deg, 1)).astype(np.float32),
                align="col",
            )
        return v.invdeg

    def _plan_args(self, kind: str) -> tuple:
        """The current version's operands for one kind (the properties
        apply the unit-weight / symmetric-transpose fallbacks)."""
        if kind == "bfs":
            return (self.E,)
        if kind == "sssp":
            return (self.E_weighted,)
        if kind == "pagerank":
            return (self.P_ell, self.dangling)
        if kind == "propagate":
            _hops, normalize = self.propagate_opts
            return (
                self.ET, self._version.X,
                self._propagate_invdeg() if normalize else None,
            )
        return (self.E, self.ET)

    #: Lane widths every warmup covers (the batcher's pow2 buckets).
    DEFAULT_WARMUP_WIDTHS = (1, 2, 4, 8, 16)

    def warmup(self, kinds: tuple[str, ...] | None = None,
               widths: tuple[int, ...] | None = None) -> dict:
        """Pre-trace/compile every (kind, width) plan by executing it
        once on an all-``PAD_ROOT`` batch (inert lanes: the program
        shape is identical, the search trivially empty) and blocking.
        After this, serving a request mix that stays inside ``kinds`` x
        ``widths`` performs ZERO traces — assert via
        ``retraces_since(mark)`` or the ``trace.serve`` obs counter.
        Returns {(kind, width): seconds}.

        ``widths=None`` (default) warms ``DEFAULT_WARMUP_WIDTHS`` PLUS
        every lane the plan store remembers for this graph's shape
        bucket (``tuner.store`` — lanes are recorded on each plan-cache
        miss), so a fresh replica pre-traces exactly what the fleet's
        traffic mix used, without re-measuring.  Explicit ``widths``
        warms exactly those.
        """
        import jax

        kinds = self.kinds() if kinds is None else kinds
        per_kind = {
            k: set(self.DEFAULT_WARMUP_WIDTHS if widths is None
                   else widths)
            for k in kinds
        }
        if widths is None:
            try:
                from ..tuner import store as plan_store

                st = plan_store.get_store()
                lanes = (
                    st.serve_lanes(plan_store.serve_plan_key(self))
                    if st is not None else ()
                )
            except Exception:
                lanes = ()
            for k, w in lanes:
                if k in per_kind:
                    per_kind[k].add(int(w))
        out = {}
        for kind in kinds:
            for w in sorted(per_kind[kind]):
                t0 = time.perf_counter()
                with self._exec_lock, obs.span(
                    "serve.warmup", kind=kind, width=int(w)
                ):
                    res = self.plan(kind, w).fn(
                        np.full(int(w), PAD_ROOT, np.int32)
                    )
                    jax.block_until_ready(res)
                out[(kind, int(w))] = time.perf_counter() - t0
        return out

    def trace_mark(self) -> int:
        """Total traces across all plans (snapshot before serving, then
        ``retraces_since`` asserts the zero-retrace contract)."""
        return sum(p.traces for p in self._plans.values())

    def retraces_since(self, mark: int) -> int:
        return self.trace_mark() - mark

    # -- execution ---------------------------------------------------------

    def _lanes_to_global(self, blocks) -> np.ndarray:
        """[pa, L, W] device blocks -> [n, W] host array (the engine's
        device->host sync) — via ``DistMultiVec.to_global`` so the
        block-layout knowledge stays in exactly one place."""
        from ..parallel.vec import DistMultiVec

        return DistMultiVec(
            blocks=blocks, length=self.nrows, align="row", grid=self.grid
        ).to_global()

    def execute(self, kind: str, sources) -> dict:
        """Run one batch: ``sources`` is the int32 lane vector (pad
        slots = ``PAD_ROOT``). Returns a dict of host arrays with the
        lane axis LAST (what ``batcher.scatter`` slices per request).
        """
        import jax.numpy as jnp

        sources = np.asarray(sources, np.int32)
        W = sources.shape[0]
        plan = self.plan(kind, W)
        with self._exec_lock, obs.span("serve.batch", kind=kind, width=W):
            res = plan.fn(jnp.asarray(sources))
            plan.executions += 1
            # "batch_niter" is BATCH metadata (the max iteration count
            # over all lanes, pad included), not a per-request fact: a
            # request's own value would vary with its batch-mates
            if kind == "bfs":
                p, l, niter = res
                return {
                    "parents": self._lanes_to_global(p),
                    "levels": self._lanes_to_global(l),
                    "batch_niter": int(niter),
                }
            if kind == "sssp":
                d, niter = res
                return {
                    "dist": self._lanes_to_global(d),
                    "batch_niter": int(niter),
                }
            if kind == "pagerank":
                x, niter = res
                return {
                    "ranks": self._lanes_to_global(x),
                    "batch_niter": int(niter),
                }
            if kind == "propagate":
                # [Fp, W] replicated features — strip the pow2 pad
                # lanes back to the true feature dim; lane axis stays
                # LAST (the batcher's scatter contract)
                from ..parallel.spgemm import host_value

                feats = host_value(res)
                return {"features": feats[: self._version.feat_dim]}
            # bc: per-lane Brandes dependency vectors
            return {"scores": self._lanes_to_global(res)}

    def stats(self) -> dict:
        # _plans_lock only: polling stats during a long batch must not
        # block on the device-holding execution lock
        with self._plans_lock:
            plans = {
                f"{k}/{w}": {
                    "traces": p.traces, "executions": p.executions,
                }
                for (k, w), p in sorted(self._plans.items())
            }
            hits, misses = self.plan_hits, self.plan_misses
        warm = self._refresh_modes.get("warm", 0)
        cold = self._refresh_modes.get("cold", 0)
        vid = self._version.vid
        return {
            "plans": plans,
            "plan_hits": hits,
            "plan_misses": misses,
            "nrows": self.nrows,
            "kinds": list(self.kinds()),
            "graph_version": vid,
            "graph_nnz": self._version.nnz,
            "swaps": self.swaps,
            # dynamic-lane freshness (round 15): how stale the cached
            # analytics are vs the served version, and how often a
            # refresh repaired instead of recomputing cold
            "freshness": {
                "refresh_modes": dict(self._refresh_modes),
                "repair_ratio": (
                    warm / (warm + cold) if warm + cold else None
                ),
                "versions_behind": (
                    max(
                        (vid - e["vid"] for e in self._analytics.values()),
                        default=0,
                    )
                ),
            },
        }
