"""FleetRouter — N replica servers behind one front door (rounds 14/16).

The horizontal half of the serving story: the pool multiplexes many
GRAPHS behind one device; the fleet multiplexes many REPLICAS of one
graph behind one router, the shape a real service scales reads with.
Properties that make it more than a load balancer:

* **One warm plan store.** Every replica resolves routing and records
  serve warmup lanes through the SAME ``tuner.store`` JSONL (already
  multi-process-safe, append-only, torn-write tolerant) — the first
  replica's traffic teaches the store which (kind, width) lanes the mix
  uses, and every later replica's ``warmup()`` replays them to
  zero-retrace steady state without re-discovering anything
  (docs/autotuning.md "Shipping plans to a fleet", now code).
* **Warm starts from snapshots.** ``FleetRouter.from_checkpoint``
  boots every replica from one ``utils.checkpoint.save_version``
  GraphVersion snapshot: bucket arrays re-upload bit-identically
  (``EllParMat.from_host_buckets`` — no dedup sort, no bucket pass), so
  a cold replica reaches the same zero-retrace state as the donor
  without ever seeing the COO.
* **Writes route HOME, versions fan OUT.** ``submit_update`` goes to
  one home replica (a single merge lineage — no cross-replica merge
  conflicts to resolve); once its merge lands, ``fan_out`` rebuilds
  each other replica's version OFF its execution lock from the home
  version's retained host COO and applies it through the existing
  atomic ``swap_graph`` — readers on every replica keep serving the old
  version mid-build and flip in one pointer swap (incremental merges
  preserve operand shapes, so the warm plans survive fleet-wide).
* **Durability + self-healing (round 16, docs/serving.md "Durability
  & self-healing").** With a durability dir configured (``wal_dir`` /
  ``COMBBLAS_WAL``), the HOME replica owns the write-ahead log and the
  background checkpointer — acknowledged writes survive any process
  crash.  A ``start_supervisor()`` thread (or deterministic
  ``supervise_once()`` calls) detects replicas whose worker thread
  died, QUARANTINES them (pending futures failed honestly — never
  silently dropped), rebuilds replacements OFF-lock from
  checkpoint+WAL (or the home's retained COO when not durable) and
  re-admits them warm; a dead HOME is first replaced by PROMOTING a
  surviving replica to the WAL's seqno frontier — the single merge
  lineage is preserved because the frontier is exactly "every
  acknowledged write".  ``drain()``/``restore()``/``rolling_restart()``
  make upgrades a first-class operation, and reads that fail
  execution-side are retried (bounded, reads only) on the next-best
  replica.

Round 17: the routing / read-retry / supervision policy moved to
``serve/policy.py`` (:class:`~combblas_tpu.serve.policy.ReplicaFleetBase`)
so the PROCESS fleet (``serve/procfleet.py`` — replicas as real OS
subprocesses with their own JAX runtimes) shares it instead of forking
it.  This class keeps the thread-hosted specifics: worker-thread death
detection, in-process rebuild/promotion, the shared exec lock.

Thread-hosted replicas: each ``Server`` owns its own engine, queue,
breakers and worker thread inside this process — the honest analog of
a replica fleet on the tier-1 virtual mesh, and exactly what one host
of a multi-host fleet runs per chip.  "Replica death" is worker-thread
death (the ``replica.death`` fault point); the multi-process fleet
(``procfleet.py``) swaps thread liveness for process liveness and
keeps everything else.
"""

from __future__ import annotations

import dataclasses
import threading
import time

from .. import obs
from .batcher import settle
from .faults import FaultInjector
from .policy import ReplicaDeadError, ReplicaFleetBase
from .scheduler import ServeConfig

__all__ = ["FleetRouter", "ReplicaDeadError"]


def _strip_wal(cfg: ServeConfig, keep: str | None) -> ServeConfig:
    """Per-replica durability config: the home replica gets the
    resolved dir, every other replica gets an EXPLICIT "off" — an
    ambient ``COMBBLAS_WAL`` must not make N replicas fight over one
    log file with N bootstrap snapshots."""
    return dataclasses.replace(
        cfg, wal_dir=(keep if keep is not None else "off")
    )


class FleetRouter(ReplicaFleetBase):
    """Front door over N replica ``Server``s sharing one plan store."""

    def __init__(self, servers, home: int = 0,
                 build_kw: dict | None = None):
        if not servers:
            raise ValueError("FleetRouter needs at least one replica")
        self.replicas = list(servers)
        if not (0 <= home < len(self.replicas)):
            raise ValueError(
                f"home replica {home} outside [0, {len(self.replicas)})"
            )
        #: Index of the replica all writes route to (one merge lineage).
        self.home = home
        #: ``build_version`` keywords fan-out rebuilds with (symmetric=
        #: etc. — must match how the replicas were built).
        self.build_kw = dict(build_kw or {})
        # ONE execution stream across replicas: thread-hosted replicas
        # share this process's device mesh, and two worker threads
        # launching collective SPMD programs CONCURRENTLY interleave
        # XLA's cross-module rendezvous (a hard deadlock, reproduced
        # on the 8-virtual-device mesh) — so every replica engine's
        # exec lock is replaced with one shared lock. A real fleet
        # with per-replica devices runs replicas as separate
        # processes (serve/procfleet.py); in-process, serialization
        # is the device truth.
        self._device_lock = threading.RLock()
        for s in self.replicas:
            s.engine._exec_lock = self._device_lock
        self._fan_lock = threading.Lock()  # one fan-out at a time
        self._scrape = None  # obs.export.ScrapeServer (serve_metrics)
        #: Fleet-level fault injection (the ``fleet.fanout`` point).
        self.faults = FaultInjector()
        #: Durability dir (the home's) — promotion / replacement source.
        self.wal_dir = self.replicas[self.home]._ckpt_dir
        self._init_policy()  # routing/supervision state (policy.py)
        obs.gauge("serve.fleet.replicas", len(self.replicas))

    def serve_metrics(self, port: int = 0, host: str = "127.0.0.1"
                      ) -> int:
        """Attach the fleet's live scrape surface (/metrics, /healthz,
        /statz — see ``Server.serve_metrics``); stopped by close()."""
        from ..obs import export

        return export.attach_scrape(self, port=port, host=host)

    # -- construction ------------------------------------------------------

    @staticmethod
    def _resolved_wal(wal_dir, config) -> str | None:
        from ..tuner import config as tuner_config

        return tuner_config.wal_dir(
            wal_dir if wal_dir is not None
            else (config.wal_dir if config is not None else None)
        )

    @staticmethod
    def build(grid, rows, cols, nrows: int, *,
              replicas: int | None = None,
              config: ServeConfig | None = None,
              home: int = 0, start: bool = True,
              wal_dir: str | None = None,
              **from_coo_kw) -> "FleetRouter":
        """Build N replicas from one COO (``COMBBLAS_FLEET_REPLICAS``
        defaults the count). The home replica keeps the host edge list
        (``keep_coo=True`` forced) — it feeds both the write lane and
        the fan-out rebuilds.  ``wal_dir`` (argument > config >
        ``COMBBLAS_WAL``) attaches the durability layer to the HOME
        replica: write-ahead log + background checkpointer."""
        from .api import Server
        from .engine import GraphEngine
        from ..tuner import config as tuner_config

        n = tuner_config.fleet_replicas(replicas)
        resolved = FleetRouter._resolved_wal(wal_dir, config)
        servers = []
        for i in range(n):
            kw = dict(from_coo_kw)
            if i == home:
                kw["keep_coo"] = True
            eng = GraphEngine.from_coo(grid, rows, cols, nrows, **kw)
            servers.append(
                Server(
                    eng,
                    _strip_wal(
                        config or ServeConfig(),
                        resolved if i == home else None,
                    ),
                    tenant=f"replica{i}",
                )
            )
        build_kw = {
            k: from_coo_kw[k] for k in ("symmetric",)
            if k in from_coo_kw
        }
        router = FleetRouter(servers, home=home, build_kw=build_kw)
        if start:
            for s in servers:
                s.start()
        return router

    @staticmethod
    def from_checkpoint(path: str, grid, *,
                        replicas: int | None = None,
                        config: ServeConfig | None = None,
                        kinds=None, home: int = 0, start: bool = True,
                        wal_dir: str | None = None,
                        symmetric: bool = True) -> "FleetRouter":
        """Boot N replicas from one ``save_version`` snapshot — the
        cold-replica warm start: every replica's version re-uploads the
        donor's exact bucket shapes (zero retraces once warmed; the
        checkpoint round-trip regression test in
        tests/test_serve_fleet.py pins this).  ``kinds=None`` derives
        the servable kinds from the snapshot's artifacts."""
        from .api import Server
        from .engine import GraphEngine
        from ..tuner import config as tuner_config
        from ..utils import checkpoint

        n = tuner_config.fleet_replicas(replicas)
        resolved = FleetRouter._resolved_wal(wal_dir, config)
        servers = []
        for i in range(n):
            # one independent version per replica: engines swap and
            # version-stamp independently, so sharing one GraphVersion
            # object would cross-wire their lineages.  Only the HOME
            # loads writable — read replicas must not each pin an
            # O(nnz) host copy of the merge-state source
            v = checkpoint.load_version(
                path, grid, writable=(i == home)
            )
            eng = GraphEngine(grid, version=v, kinds=kinds)
            servers.append(
                Server(
                    eng,
                    _strip_wal(
                        config or ServeConfig(),
                        resolved if i == home else None,
                    ),
                    tenant=f"replica{i}",
                )
            )
        router = FleetRouter(
            servers, home=home, build_kw={"symmetric": symmetric}
        )
        if start:
            for s in servers:
                s.start()
        return router

    @staticmethod
    def from_recovery(grid, *, replicas: int | None = None,
                      config: ServeConfig | None = None,
                      kinds=None, home: int = 0, start: bool = True,
                      wal_dir: str | None = None,
                      symmetric: bool = True) -> "FleetRouter":
        """Boot a whole fleet from crash recovery (round 16): every
        replica's version = latest valid snapshot + WAL-suffix replay
        (``dynamic.wal.recover_version`` — bit-exact with the fleet
        that crashed, every acknowledged write included), the home
        re-attached to the WAL at the seqno frontier.  With the shared
        plan store populated, ``warmup()`` replays the remembered
        lanes — warm plans, zero retraces, zero re-measurement."""
        from .api import Server
        from .engine import GraphEngine
        from ..dynamic import wal as dyn_wal
        from ..tuner import config as tuner_config

        resolved = FleetRouter._resolved_wal(wal_dir, config)
        if resolved is None:
            raise ValueError(
                "FleetRouter.from_recovery needs a durability dir "
                "(wal_dir=, ServeConfig.wal_dir or COMBBLAS_WAL)"
            )
        n = tuner_config.fleet_replicas(replicas)
        servers = []
        for i in range(n):
            cfg_i = _strip_wal(
                config or ServeConfig(), resolved if i == home else None
            )
            if i == home:
                servers.append(Server.from_recovery(
                    grid, cfg_i, kinds=kinds, tenant=f"replica{i}"
                ))
                continue
            v = dyn_wal.recover(resolved, grid, kinds=kinds)
            eng = GraphEngine(grid, version=v, kinds=kinds)
            servers.append(
                Server(eng, cfg_i, tenant=f"replica{i}")
            )
        router = FleetRouter(
            servers, home=home, build_kw={"symmetric": symmetric}
        )
        if start:
            for s in servers:
                s.start()
        return router

    # -- read path: routing/spillover/read-retry live in policy.py ---------

    # -- write path --------------------------------------------------------

    def submit_update(self, ops, fan_out: bool = True):
        """Route a mutation batch to the HOME replica; once its merge
        lands, fan the new version out to every other replica through
        the atomic swap. The returned future resolves (with the home
        merge payload plus ``fanned_out``) after the serving fleet
        runs the new version — a replica whose rebuild failed mid-fan
        LAGS visibly (``versions_behind``, degraded health, retried on
        the next fan-out) instead of failing the write."""
        from concurrent.futures import Future

        home = self.replicas[self.home]
        inner = home.submit_update(ops)
        if not fan_out:
            return inner
        outer: Future = Future()

        def _after_merge(f):
            exc = f.exception()
            if exc is not None:
                settle(outer, exc=exc)
                return
            payload = dict(f.result())
            # the home server's write-lane trace rides on the inner
            # future; this callback runs INSIDE its settle (before the
            # trace is finished), so a fan-out mark lands in the
            # committed record between the swap and settle stages
            tr = getattr(f, "_combblas_trace", None)
            try:
                payload["fanned_out"] = self.fan_out()
                payload["lagging"] = self.lagging()
                if tr is not None:
                    tr.mark("fanout")
            except Exception as e:  # fan_out itself tolerates
                # per-replica failures; reaching here means the fan
                # could not run at all (e.g. the home lost its COO) —
                # a divergence the caller must see
                settle(outer, exc=e)
                return
            settle(outer, result=payload)

        inner.add_done_callback(_after_merge)
        return outer

    def fan_out(self) -> int:
        """Propagate the home replica's CURRENT version to every other
        serving replica: rebuild each replica's own version from the
        home version's retained host COO (off that replica's execution
        lock — its readers keep serving) and swap atomically.

        Round 16: a replica whose rebuild/swap FAILS (or that is
        dead/draining) no longer aborts the fleet — it stays on its
        old version, counted and gauged per replica
        (``serve.fleet.versions_behind``), degrades fleet ``health()``
        and is RETRIED on the next fan-out (every fan-out rebuilds all
        lagging replicas from the current home version).  Returns
        replicas updated this call."""
        with self._fan_lock:
            v = self.replicas[self.home].engine.version
            if v.host_coo is None:
                raise ValueError(
                    "fan_out needs the home replica's host edge list: "
                    "build the fleet via FleetRouter.build (or "
                    "from_coo(keep_coo=True))"
                )
            rows, cols, _nc = v.host_coo
            weights = v.host_weights
            self._fan_gen += 1
            gen = self._fan_gen
            t0 = time.perf_counter()
            n = 0
            for i, srv in enumerate(self.replicas):
                if i == self.home:
                    self._replica_gen[i] = gen
                    continue
                if i in self._draining or not srv.is_serving():
                    # dead/draining replicas lag on purpose — the
                    # supervisor (or restore()) rebuilds them at the
                    # frontier, where they catch up in one step
                    continue
                try:
                    self.faults.check("fleet.fanout", replica=i)
                    nv = srv.engine.build_version(
                        rows, cols, weights=weights, keep_coo=False,
                        **self.build_kw,
                    )
                    srv.swap_graph(nv)
                    self._replica_gen[i] = gen
                    n += 1
                except Exception:
                    obs.count("serve.fleet.fanout_failed", replica=i)
            self.fanouts += 1
            obs.count("serve.fleet.fanout")
            obs.observe(
                "serve.fleet.fanout_s", time.perf_counter() - t0
            )
            for i in range(len(self.replicas)):
                obs.gauge(
                    "serve.fleet.versions_behind",
                    gen - self._replica_gen[i], replica=i,
                )
            return n

    # -- self-healing: thread-fleet liveness + heal verbs ------------------

    def _dead(self, i: int) -> bool:
        """Worker-thread death: started once, no longer running, and
        not closed by us (closed = deliberate)."""
        s = self.replicas[i]
        w = s._worker
        return (
            w is not None and not w.is_alive()
            and not s._stop and not s.scheduler.closed
        )

    def promote(self, new_home: int | None = None) -> int:
        """Promote a surviving replica to HOME (round 16) — the
        dead-home failover.  The single merge lineage is preserved by
        promoting AT THE WAL'S SEQNO FRONTIER: the new home's version
        is ``recover_version`` (latest snapshot + full WAL-suffix
        replay), which contains exactly every ACKNOWLEDGED write —
        including writes the dead home had buffered but not merged.
        Those buffered writes' futures are failed honestly
        (``ReplicaDeadError``; the data itself is durable and present
        at the frontier — the futures' callers just never got their
        merge confirmation).  The WAL and checkpointer re-attach to
        the new home; the dead ex-home becomes a regular replica slot
        for ``_replace_replica``.  Returns the new home index."""
        with self._sup_lock:
            old = self.home
            old_srv = self.replicas[old]
            if self.wal_dir is None:
                # no WAL: the un-merged buffered writes died with the
                # home (there is no durable record to promote from) —
                # fail them honestly and surface the degraded fleet;
                # reads keep serving on the other replicas
                old_srv.quarantine(ReplicaDeadError(
                    f"home replica {old} died without a WAL; buffered "
                    "writes are lost (configure wal_dir for durable "
                    "failover)"
                ))
                raise RuntimeError(
                    "home promotion needs fleet durability (wal_dir / "
                    "COMBBLAS_WAL): without a write-ahead log the "
                    "write lineage died with the home replica"
                )
            if new_home is None:
                cands = [
                    i for i in self._route_order()
                    if i != old and self.replicas[i].is_serving()
                ]
                if not cands:
                    raise RuntimeError(
                        "no serving replica available to promote"
                    )
                new_home = cands[0]
            # 1. fail the dead home's pending futures honestly (reads
            #    AND buffered writes; acknowledged writes are in the
            #    WAL and reappear at the recovered frontier below)
            old_srv.quarantine(ReplicaDeadError(
                f"home replica {old} died; promoting replica "
                f"{new_home} at the WAL frontier (acknowledged "
                "writes are durable and replayed there)"
            ))
            # 2. bring the new home to the frontier: snapshot + full
            #    WAL-suffix replay = every acknowledged write
            from ..dynamic import wal as dyn_wal

            ns = self.replicas[new_home]
            v = dyn_wal.recover(
                self.wal_dir, ns.engine.grid, kinds=ns.engine.kinds()
            )
            ns.swap_graph(v)
            # 3. the write lane follows the lineage: WAL + background
            #    checkpointer re-attach to the new home
            ns.attach_durability(self.wal_dir)
            # the recovered version's bucket shapes (the donor's
            # sticky layout) may differ from the fan-out-rebuilt ones
            # this replica served: re-warm so steady state stays
            # zero-retrace after the promotion
            try:
                ns.warmup()
            except Exception:
                obs.count(
                    "serve.fleet.supervisor", action="warmup_error"
                )
            self.home = new_home
            self._replica_gen[new_home] = self._fan_gen
            self.promotions += 1
            obs.count("serve.fleet.promotions")
            # propagate the recovered frontier to the SURVIVING
            # replicas NOW: the recovery may contain acknowledged
            # writes the dead home never fanned out, and waiting for
            # the next write (possibly never, on a read-heavy
            # service) would serve split-brain reads while health()
            # reports ok.  Best-effort: a failed rebuild lags visibly
            # (versions_behind / degraded health) as usual.
            try:
                self.fan_out()
            except Exception:
                obs.count(
                    "serve.fleet.supervisor", action="fanout_error"
                )
            return new_home

    def _spawn_replica(self, i: int, engine, started: bool) -> None:
        """Install a fresh ``Server`` shell around ``engine`` at slot
        ``i`` (shared exec lock, same tenant label), warmed from the
        shared plan store before it takes traffic."""
        from .api import Server

        cfg = _strip_wal(
            self.replicas[i].config,
            self.wal_dir if i == self.home else None,
        )
        engine._exec_lock = self._device_lock
        new = Server(engine, cfg, tenant=f"replica{i}")
        if started:
            new.start()
        # warm BEFORE admitting traffic: the shared store replays the
        # fleet's remembered lanes, so the replacement reaches
        # zero-retrace steady state off the routing path
        try:
            new.warmup()
        except Exception:
            obs.count("serve.fleet.supervisor", action="warmup_error")
        self.replicas[i] = new
        self._replica_gen[i] = self._fan_gen
        self._needs_rebuild.discard(i)  # the slot is healed

    def _replace_replica(self, i: int) -> None:
        """Rebuild a DEAD replica off-lock and re-admit it: from
        checkpoint+WAL when durable (the crash-consistent source),
        else from the home version's retained host COO (the fan-out
        recipe).  The dead server's pending futures were already
        failed by ``promote``/``quarantine`` — or are failed here."""
        from .engine import GraphEngine

        old = self.replicas[i]
        if not old.scheduler.closed:  # promote() may have quarantined
            old.quarantine(ReplicaDeadError(
                f"replica {i} worker died; the fleet supervisor is "
                "rebuilding a replacement"
            ))
        grid = old.engine.grid
        kinds = old.engine.kinds()
        if self.wal_dir is not None:
            from ..dynamic import wal as dyn_wal

            v = dyn_wal.recover(self.wal_dir, grid, kinds=kinds)
            engine = GraphEngine(grid, version=v, kinds=kinds)
        else:
            hv = self.replicas[self.home].engine.version
            if hv.host_coo is None:
                raise RuntimeError(
                    "cannot rebuild a dead replica: no durability dir "
                    "and the home retained no host COO"
                )
            rows, cols, _nc = hv.host_coo
            engine = GraphEngine.from_coo(
                grid, rows, cols, int(hv.nrows),
                weights=hv.host_weights, kinds=kinds,
                # a rebuilt HOME must keep feeding the write lane and
                # the fan-out rebuilds (the non-durable fresh lineage)
                keep_coo=(i == self.home),
                **self.build_kw,
            )
        self._spawn_replica(i, engine, started=True)
        self.replacements += 1
        obs.count("serve.fleet.replaced", replica=i)

    def drain(self, i: int, timeout: float = 30.0) -> None:
        """Take replica ``i`` out of rotation and close it CLEANLY —
        queued reads execute, buffered writes merge (and, on a durable
        home, checkpoint), then the worker stops.  The first half of a
        rolling restart; ``restore()`` re-admits the slot.  Draining
        the HOME makes writes reject until it is restored (one write
        lineage — by design)."""
        with self._sup_lock:
            if not (0 <= i < len(self.replicas)):
                raise ValueError(f"no replica {i}")
            self._draining.add(i)
            self._drain_gen[i] = self._fan_gen
        obs.count("serve.fleet.drained", replica=i)
        self._fleet_event("drain", replica=i, home=(i == self.home))
        self.replicas[i].close(drain=True, timeout=timeout)

    def restore(self, i: int) -> None:
        """Re-admit a drained replica: a fresh ``Server`` shell around
        the SAME (healthy, warm) engine — plan cache intact, zero
        rebuild, zero retraces.  A durable home re-attaches the WAL at
        the frontier it drained to.  A replica that missed fan-outs
        while draining is healed with one immediate fan-out instead of
        silently serving stale versions."""
        with self._sup_lock:
            if i not in self._draining:
                raise ValueError(
                    f"replica {i} is not draining (drain() first)"
                )
            self._spawn_replica(i, self.replicas[i].engine,
                                started=True)
            if i != self.home:
                # the engine's content is whatever it drained at —
                # fan-outs during the drain skipped it on purpose
                self._replica_gen[i] = self._drain_gen.pop(
                    i, self._fan_gen
                )
            else:
                self._drain_gen.pop(i, None)
            self._draining.discard(i)
        obs.count("serve.fleet.restored", replica=i)
        self._fleet_event("restore", replica=i, home=(i == self.home))
        if (
            self._replica_gen[i] < self._fan_gen
            and self.replicas[self.home].engine.version.host_coo
            is not None
        ):
            self.fan_out()  # catch the restored replica up NOW

    def rolling_restart(self, timeout: float = 30.0) -> int:
        """Upgrade-style rolling restart: drain + restore each replica
        in turn, non-home replicas first, the home LAST (its drain
        flushes the write lane through merge + checkpoint, so the
        restarted home resumes at a clean frontier).  At most one
        replica is out of rotation at a time; reads keep serving
        throughout.  Returns replicas restarted."""
        order = [
            i for i in range(len(self.replicas)) if i != self.home
        ] + [self.home]
        n = 0
        for i in order:
            self.drain(i, timeout=timeout)
            self.restore(i)
            n += 1
        obs.count("serve.fleet.rolling_restarts")
        self._fleet_event("rolling_restart", replicas=n)
        return n

    # -- lifecycle / introspection -----------------------------------------

    def warmup(self, **kw) -> dict:
        """Warm every replica. With the shared plan store populated
        (a prior replica's traffic), each replica pre-traces the
        remembered lanes — the fleet-wide zero-retrace claim."""
        return {
            i: srv.warmup(**kw) for i, srv in enumerate(self.replicas)
        }

    def close(self, drain: bool = True, timeout: float = 30.0) -> None:
        self.stop_supervisor(timeout)
        # non-home replicas first, the home LAST: its close flushes
        # pending write merges (drain=True), and a fan-out callback
        # running inside those merges' settle can still swap the
        # already-stopped replicas' engines consistently
        order = [
            i for i in range(len(self.replicas)) if i != self.home
        ] + [self.home]
        for i in order:
            self.replicas[i].close(drain=drain, timeout=timeout)
        if self._scrape is not None:
            from ..obs import export

            export.detach_scrape(self)

    def __enter__(self) -> "FleetRouter":
        for srv in self.replicas:
            srv.start()
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def stats(self) -> dict:
        return {
            "replicas": len(self.replicas),
            "home": self.home,
            "routed": list(self.submitted),
            "spillovers": self.spillovers,
            "fanouts": self.fanouts,
            "lagging": self.lagging(),
            "promotions": self.promotions,
            "replacements": self.replacements,
            "read_retries": self.read_retries,
            "draining": sorted(self._draining),
            "supervisor_alive": self._supervisor_alive(),
            "wal_dir": self.wal_dir,
            "per_replica": {
                i: srv.stats() for i, srv in enumerate(self.replicas)
            },
        }

    def health(self) -> dict:
        per = {i: srv.health() for i, srv in enumerate(self.replicas)}
        statuses = {h["status"] for h in per.values()}
        lagging = self.lagging()
        burns = {
            i: h["slo"]["burn"]
            for i, h in per.items() if h.get("slo") is not None
        }
        return {
            "status": self._fold_status(statuses, lagging),
            "replicas": per,
            "home": self.home,
            # round 16: replicas behind the home's latest fan-out
            # (failed rebuilds / dead replicas) degrade the fleet
            # until the next fan-out or the supervisor heals them
            "lagging": lagging,
            "draining": sorted(self._draining),
            "supervisor_alive": self._supervisor_alive(),
            "durable": self.wal_dir is not None,
            # fleet-wide SLO budget burn (round 15): worst replica —
            # the pageable number when replicas share one SLO
            "slo_burn": burns,
            "slo_burn_worst": max(burns.values()) if burns else None,
        }
