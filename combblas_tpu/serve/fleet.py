"""FleetRouter — N replica servers behind one front door (round 14).

The horizontal half of the serving story: the pool multiplexes many
GRAPHS behind one device; the fleet multiplexes many REPLICAS of one
graph behind one router, the shape a real service scales reads with.
Three properties make it more than a load balancer:

* **One warm plan store.** Every replica resolves routing and records
  serve warmup lanes through the SAME ``tuner.store`` JSONL (already
  multi-process-safe, append-only, torn-write tolerant) — the first
  replica's traffic teaches the store which (kind, width) lanes the mix
  uses, and every later replica's ``warmup()`` replays them to
  zero-retrace steady state without re-discovering anything
  (docs/autotuning.md "Shipping plans to a fleet", now code).
* **Warm starts from snapshots.** ``FleetRouter.from_checkpoint``
  boots every replica from one ``utils.checkpoint.save_version``
  GraphVersion snapshot: bucket arrays re-upload bit-identically
  (``EllParMat.from_host_buckets`` — no dedup sort, no bucket pass), so
  a cold replica reaches the same zero-retrace state as the donor
  without ever seeing the COO.
* **Writes route HOME, versions fan OUT.** ``submit_update`` goes to
  one home replica (a single merge lineage — no cross-replica merge
  conflicts to resolve); once its merge lands, ``fan_out`` rebuilds
  each other replica's version OFF its execution lock from the home
  version's retained host COO and applies it through the existing
  atomic ``swap_graph`` — readers on every replica keep serving the old
  version mid-build and flip in one pointer swap (incremental merges
  preserve operand shapes, so the warm plans survive fleet-wide).

Reads route to the least-loaded replica (queue depth, round-robin tie
break) and SPILL OVER on backpressure: only when every replica rejects
does the caller see the last ``BackpressureError``.

Thread-hosted replicas: each ``Server`` owns its own engine, queue,
breakers and worker thread inside this process — the honest analog of
a replica fleet on the tier-1 virtual mesh, and exactly what one host
of a multi-host fleet runs per chip.
"""

from __future__ import annotations

import itertools
import threading
import time
from concurrent.futures import Future

from .. import obs
from .batcher import settle
from .scheduler import BackpressureError, ServeConfig


class FleetRouter:
    """Front door over N replica ``Server``s sharing one plan store."""

    def __init__(self, servers, home: int = 0, build_kw: dict | None = None):
        if not servers:
            raise ValueError("FleetRouter needs at least one replica")
        self.replicas = list(servers)
        if not (0 <= home < len(self.replicas)):
            raise ValueError(
                f"home replica {home} outside [0, {len(self.replicas)})"
            )
        #: Index of the replica all writes route to (one merge lineage).
        self.home = home
        #: ``build_version`` keywords fan-out rebuilds with (symmetric=
        #: etc. — must match how the replicas were built).
        self.build_kw = dict(build_kw or {})
        # ONE execution stream across replicas: thread-hosted replicas
        # share this process's device mesh, and two worker threads
        # launching collective SPMD programs CONCURRENTLY interleave
        # XLA's cross-module rendezvous (a hard deadlock, reproduced
        # on the 8-virtual-device mesh) — so every replica engine's
        # exec lock is replaced with one shared lock. A real fleet
        # with per-replica devices runs replicas as separate
        # processes; in-process, serialization is the device truth.
        self._device_lock = threading.RLock()
        for s in self.replicas:
            s.engine._exec_lock = self._device_lock
        self._rr = itertools.count()
        self._fan_lock = threading.Lock()  # one fan-out at a time
        self.submitted: list[int] = [0] * len(self.replicas)
        self.spillovers = 0
        self.fanouts = 0
        self._scrape = None  # obs.export.ScrapeServer (serve_metrics)
        obs.gauge("serve.fleet.replicas", len(self.replicas))

    def serve_metrics(self, port: int = 0, host: str = "127.0.0.1"
                      ) -> int:
        """Attach the fleet's live scrape surface (/metrics, /healthz,
        /statz — see ``Server.serve_metrics``); stopped by close()."""
        from ..obs import export

        return export.attach_scrape(self, port=port, host=host)

    # -- construction ------------------------------------------------------

    @staticmethod
    def build(grid, rows, cols, nrows: int, *,
              replicas: int | None = None,
              config: ServeConfig | None = None,
              home: int = 0, start: bool = True,
              **from_coo_kw) -> "FleetRouter":
        """Build N replicas from one COO (``COMBBLAS_FLEET_REPLICAS``
        defaults the count). The home replica keeps the host edge list
        (``keep_coo=True`` forced) — it feeds both the write lane and
        the fan-out rebuilds."""
        from .api import Server
        from .engine import GraphEngine
        from ..tuner import config as tuner_config

        n = tuner_config.fleet_replicas(replicas)
        servers = []
        for i in range(n):
            kw = dict(from_coo_kw)
            if i == home:
                kw["keep_coo"] = True
            eng = GraphEngine.from_coo(grid, rows, cols, nrows, **kw)
            servers.append(
                Server(eng, config or ServeConfig(),
                       tenant=f"replica{i}")
            )
        build_kw = {
            k: from_coo_kw[k] for k in ("symmetric",)
            if k in from_coo_kw
        }
        router = FleetRouter(servers, home=home, build_kw=build_kw)
        if start:
            for s in servers:
                s.start()
        return router

    @staticmethod
    def from_checkpoint(path: str, grid, *,
                        replicas: int | None = None,
                        config: ServeConfig | None = None,
                        kinds=None, home: int = 0, start: bool = True,
                        symmetric: bool = True) -> "FleetRouter":
        """Boot N replicas from one ``save_version`` snapshot — the
        cold-replica warm start: every replica's version re-uploads the
        donor's exact bucket shapes (zero retraces once warmed; the
        checkpoint round-trip regression test in
        tests/test_serve_fleet.py pins this).  ``kinds=None`` derives
        the servable kinds from the snapshot's artifacts."""
        from .api import Server
        from .engine import GraphEngine
        from ..tuner import config as tuner_config
        from ..utils import checkpoint

        n = tuner_config.fleet_replicas(replicas)
        servers = []
        for i in range(n):
            # one independent version per replica: engines swap and
            # version-stamp independently, so sharing one GraphVersion
            # object would cross-wire their lineages
            v = checkpoint.load_version(path, grid)
            eng = GraphEngine(grid, version=v, kinds=kinds)
            servers.append(
                Server(eng, config or ServeConfig(),
                       tenant=f"replica{i}")
            )
        router = FleetRouter(
            servers, home=home, build_kw={"symmetric": symmetric}
        )
        if start:
            for s in servers:
                s.start()
        return router

    # -- read path ---------------------------------------------------------

    def _route_order(self) -> list[int]:
        """Replica indices, least queue depth first; ties broken by a
        rotating offset so equal-depth replicas share evenly."""
        depths = [s.scheduler.depth() for s in self.replicas]
        off = next(self._rr) % len(self.replicas)
        return sorted(
            range(len(self.replicas)),
            key=lambda i: (depths[i], (i - off) % len(self.replicas)),
        )

    def submit(self, kind: str, root, timeout_s: float | None = None):
        """Route one query to the least-loaded replica, spilling to
        the next on backpressure/breaker rejection; raises the LAST
        rejection only when every replica refused."""
        last_exc: Exception | None = None
        for i in self._route_order():
            try:
                fut = self.replicas[i].submit(
                    kind, root, timeout_s=timeout_s
                )
            except BackpressureError as e:
                self.spillovers += 1
                obs.count("serve.fleet.spillover", replica=i)
                last_exc = e
                continue
            self.submitted[i] += 1
            obs.count("serve.fleet.submitted", replica=i)
            return fut
        raise last_exc  # every replica rejected

    def submit_many(self, kind: str, roots,
                    timeout_s: float | None = None) -> list:
        """Bulk submit through the router. Unlike a single server's
        prefix semantics, spillover means a LATER root can still land
        after one was rejected fleet-wide — so each rejected root fails
        its OWN future and admission continues."""
        out = []
        for r in roots:
            try:
                out.append(self.submit(kind, r, timeout_s=timeout_s))
            except BackpressureError as e:
                f: Future = Future()
                f.set_exception(e)
                out.append(f)
        return out

    # -- write path --------------------------------------------------------

    def submit_update(self, ops, fan_out: bool = True):
        """Route a mutation batch to the HOME replica; once its merge
        lands, fan the new version out to every other replica through
        the atomic swap. The returned future resolves (with the home
        merge payload plus ``fanned_out``) after the whole fleet
        serves the new version."""
        home = self.replicas[self.home]
        inner = home.submit_update(ops)
        if not fan_out:
            return inner
        outer: Future = Future()

        def _after_merge(f):
            exc = f.exception()
            if exc is not None:
                settle(outer, exc=exc)
                return
            payload = dict(f.result())
            # the home server's write-lane trace rides on the inner
            # future; this callback runs INSIDE its settle (before the
            # trace is finished), so a fan-out mark lands in the
            # committed record between the swap and settle stages
            tr = getattr(f, "_combblas_trace", None)
            try:
                payload["fanned_out"] = self.fan_out()
                if tr is not None:
                    tr.mark("fanout")
            except Exception as e:  # the home merge LANDED; a failed
                # fan-out is a divergence the caller must see
                settle(outer, exc=e)
                return
            settle(outer, result=payload)

        inner.add_done_callback(_after_merge)
        return outer

    def fan_out(self) -> int:
        """Propagate the home replica's CURRENT version to every other
        replica: rebuild each replica's own version from the home
        version's retained host COO (off that replica's execution
        lock — its readers keep serving) and swap atomically. Returns
        replicas updated."""
        with self._fan_lock:
            v = self.replicas[self.home].engine.version
            if v.host_coo is None:
                raise ValueError(
                    "fan_out needs the home replica's host edge list: "
                    "build the fleet via FleetRouter.build (or "
                    "from_coo(keep_coo=True))"
                )
            rows, cols, _nc = v.host_coo
            weights = v.host_weights
            t0 = time.perf_counter()
            n = 0
            for i, srv in enumerate(self.replicas):
                if i == self.home:
                    continue
                nv = srv.engine.build_version(
                    rows, cols, weights=weights, keep_coo=False,
                    **self.build_kw,
                )
                srv.swap_graph(nv)
                n += 1
            self.fanouts += 1
            obs.count("serve.fleet.fanout")
            obs.observe(
                "serve.fleet.fanout_s", time.perf_counter() - t0
            )
            return n

    # -- lifecycle / introspection -----------------------------------------

    def warmup(self, **kw) -> dict:
        """Warm every replica. With the shared plan store populated
        (a prior replica's traffic), each replica pre-traces the
        remembered lanes — the fleet-wide zero-retrace claim."""
        return {
            i: srv.warmup(**kw) for i, srv in enumerate(self.replicas)
        }

    def close(self, drain: bool = True, timeout: float = 30.0) -> None:
        for srv in self.replicas:
            srv.close(drain=drain, timeout=timeout)
        if self._scrape is not None:
            from ..obs import export

            export.detach_scrape(self)

    def __enter__(self) -> "FleetRouter":
        for srv in self.replicas:
            srv.start()
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def stats(self) -> dict:
        return {
            "replicas": len(self.replicas),
            "home": self.home,
            "routed": list(self.submitted),
            "spillovers": self.spillovers,
            "fanouts": self.fanouts,
            "per_replica": {
                i: srv.stats() for i, srv in enumerate(self.replicas)
            },
        }

    def health(self) -> dict:
        per = {i: srv.health() for i, srv in enumerate(self.replicas)}
        statuses = {h["status"] for h in per.values()}
        if statuses <= {"ok"}:
            status = "ok"
        elif "ok" in statuses or "degraded" in statuses:
            status = "degraded"  # something still serves
        else:
            status = "down"
        burns = {
            i: h["slo"]["burn"]
            for i, h in per.items() if h.get("slo") is not None
        }
        return {
            "status": status,
            "replicas": per,
            "home": self.home,
            # fleet-wide SLO budget burn (round 15): worst replica —
            # the pageable number when replicas share one SLO
            "slo_burn": burns,
            "slo_burn_worst": max(burns.values()) if burns else None,
        }
